"""Control-plane message payloads.

Capability parity: dlrover/python/common/grpc.py:118-417 — every master↔agent
interaction is a typed dataclass carried over a deliberately minimal 2-RPC
service (`get`, `report`). Unlike the reference's bare pickle, deserialization
here goes through a restricted unpickler that only admits classes defined in
this module (plus builtins), so a compromised peer can't instantiate arbitrary
objects.
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Dict, List, Optional


class Message:
    """Base class for all control-plane payloads."""


# --------------------------------------------------------------------------
# Serialization with a class allowlist.
# --------------------------------------------------------------------------

_SAFE_BUILTINS = {
    "dict", "list", "tuple", "set", "frozenset", "bytes", "bytearray",
    "str", "int", "float", "bool", "complex", "NoneType",
}


class _RestrictedUnpickler(pickle.Unpickler):
    def find_class(self, module, name):
        # Dotted names resolve attribute chains (e.g. "pickle.loads" via any
        # allowed module) — never allow them.
        if "." in name:
            raise pickle.UnpicklingError(
                f"forbidden dotted name in control-plane message: "
                f"{module}.{name}"
            )
        if module == "dlrover_tpu.common.messages":
            candidate = globals().get(name)
            if isinstance(candidate, type) and issubclass(candidate, Message):
                return candidate
        if module == "builtins" and name in _SAFE_BUILTINS:
            return super().find_class(module, name)
        raise pickle.UnpicklingError(
            f"forbidden class in control-plane message: {module}.{name}"
        )


def serialize_message(message: Message) -> bytes:
    return pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize_message(data: bytes) -> Optional[Message]:
    if not data:
        return None
    return _RestrictedUnpickler(io.BytesIO(data)).load()


# --------------------------------------------------------------------------
# Generic / bookkeeping
# --------------------------------------------------------------------------


@dataclass
class BaseRequest(Message):
    node_id: int = -1
    node_type: str = ""


@dataclass
class Response(Message):
    success: bool = True
    reason: str = ""


# --------------------------------------------------------------------------
# Dynamic data sharding (reference: TaskRequest/Task/ShardConfig …)
# --------------------------------------------------------------------------


@dataclass
class Shard(Message):
    start: int = 0
    end: int = 0
    indices: Optional[List[int]] = None  # for shuffled text datasets
    record_offsets: Optional[List[int]] = None


@dataclass
class Task(Message):
    task_id: int = -1
    task_type: str = ""          # TaskType.*
    dataset_name: str = ""
    shard: Shard = field(default_factory=Shard)
    epoch: int = 0

    @property
    def is_empty(self) -> bool:
        return self.task_id < 0


@dataclass
class TaskRequest(Message):
    dataset_name: str = ""
    worker_id: int = -1


@dataclass
class TaskResult(Message):
    dataset_name: str = ""
    task_id: int = -1
    worker_id: int = -1
    success: bool = True
    err_message: str = ""


@dataclass
class DatasetShardParams(Message):
    """Register a dataset for dynamic sharding."""

    dataset_name: str = ""
    dataset_size: int = 0
    shard_size: int = 0          # records per shard (batch_size × steps)
    num_epochs: int = 1
    shuffle: bool = False
    task_type: str = ""
    storage_type: str = "text"   # "table" (range-only) | "text" (indices)
    num_minibatches_per_shard: int = 0


@dataclass
class ShardCheckpointRequest(Message):
    dataset_name: str = ""


@dataclass
class ShardCheckpoint(Message):
    dataset_name: str = ""
    content: str = ""            # JSON-encoded DatasetShardCheckpoint


@dataclass
class DatasetMeta(Message):
    dataset_name: str = ""


@dataclass
class DatasetEpochInfo(Message):
    dataset_name: str = ""
    epoch: int = 0


@dataclass
class TaskCounts(Message):
    dataset_name: str = ""
    todo: int = 0
    doing: int = 0
    done: int = 0


# --------------------------------------------------------------------------
# Rendezvous (reference: JoinRendezvousRequest / CommWorldRequest …)
# --------------------------------------------------------------------------


@dataclass
class JoinRendezvousRequest(Message):
    node_id: int = -1
    node_rank: int = -1
    local_world_size: int = 1    # devices (chips) on this host
    rdzv_name: str = ""
    node_ip: str = ""
    # span parent context (obs.current_context()) so the master-side join
    # span shares the agent's trace; {} = sender predates the field
    trace: Dict[str, str] = field(default_factory=dict)
    # ICI slice this host belongs to (multi-slice hierarchical DP):
    # activates slice-scoped rendezvous — per-slice worlds and
    # generation tokens, a slice-local failure re-forms only that
    # slice. -1 = single-slice job / sender predates the field.
    slice_id: int = -1


@dataclass
class JoinRendezvousResult(Message):
    # The round this joiner will be placed in; the agent re-joins if it sees
    # get_comm_world advance past this round without including it (world
    # invalidated by a member death, or dropped by node_unit rounding).
    round: int = 0
    # Master generation token (bumped each master (re)start over one state
    # lineage): agents remember it and present it on reconnect so a
    # restarted master can tell re-registration from a new joiner.
    generation: int = 0
    # Peer-to-peer restore plan for this rank (checkpoint/peer_restore.py):
    # JSON {"epoch", "step", "entries": {shard_key: {"rank", "addr"}}}
    # mapping each staged shard to a surviving donor. "" = no donors (or
    # sender predates the field); the worker re-fetches via
    # RestorePlanRequest anyway — this copy serves workers with no master
    # client and records the plan at the re-rendezvous cut.
    restore_plan_json: str = ""
    # Online parallelism re-plan for the joining world
    # (parallel/planner.py): the deterministic DP×TP×PP(×DCN) mesh +
    # batch/accumulation shape chosen for the NEW world size, stamped
    # with the rendezvous generation token and world epoch. "" = no
    # planner input yet / sender predates the field; workers re-fetch
    # fresh via ShardPlanRequest at loop build.
    shard_plan_json: str = ""
    # Coordination-tier address (master/coord_service.py): hot KV
    # traffic (dcn/ gradient exchange, coord/ barriers) dials this
    # instead of the control tier. "" = tier not split out.
    coord_addr: str = ""


@dataclass
class ReconnectRequest(Message):
    """An agent in master-lost mode re-registering with a (possibly
    restarted) master. Carries everything the master needs to decide
    whether the agent's cached world is still valid."""

    node_id: int = -1
    node_rank: int = -1
    node_type: str = ""
    local_world_size: int = 1
    rdzv_name: str = ""
    # the generation the agent last saw (0 = it never learned one)
    generation: int = 0
    # the last completed round the agent was placed in (-1 = none)
    rdzv_round: int = -1
    # see JoinRendezvousRequest.slice_id
    slice_id: int = -1


@dataclass
class ReconnectResult(Message):
    generation: int = 0
    # True: the agent's rank is in the master's latest world for the
    # round the agent reported — keep the worker running. False: the
    # world moved on (or was never restored); re-join rendezvous.
    world_intact: bool = False
    round: int = -1
    # the (possibly promoted) master's coordination-tier address; a
    # standby's tier binds a fresh port, so reconnecting clients must
    # re-learn it ("" = tier not split out)
    coord_addr: str = ""


@dataclass
class LeaveRendezvousRequest(Message):
    """A joiner abandoning an uncompleted round (poll deadline). Without
    this, its stale entry lets a late partner complete a round with a
    peer that already gave up."""

    node_id: int = -1
    node_rank: int = -1
    rdzv_name: str = ""


@dataclass
class WaitingNodeNumRequest(Message):
    node_id: int = -1
    rdzv_name: str = ""


@dataclass
class WaitingNodeNum(Message):
    waiting_num: int = 0


@dataclass
class CommWorldRequest(Message):
    node_id: int = -1
    rdzv_name: str = ""


@dataclass
class CommWorld(Message):
    rdzv_name: str = ""
    round: int = 0
    group: int = 0
    # node_rank → local_world_size
    world: Dict[int, int] = field(default_factory=dict)


@dataclass
class PeerStoreReport(Message):
    """An agent advertising its host's staged peer-state cache
    (checkpoint/peer_restore.py): which shards of which step its donor
    server can serve to a replacement rank. step < 0 (or no keys) =
    nothing staged — the master drops the registration."""

    node_id: int = -1
    node_rank: int = -1
    addr: str = ""               # donor server "ip:port"
    step: int = -1
    rdzv_name: str = ""
    keys: List[str] = field(default_factory=list)
    total_bytes: int = 0
    # donor's ICI slice: restore plans prefer same-slice donors (ICI
    # bandwidth) before cross-slice (DCN) ones. -1 = no slice.
    slice_id: int = -1


@dataclass
class RestorePlanRequest(Message):
    """A restoring worker asking for a (fresh) peer-restore plan —
    or, with epoch_only, just the current world epoch: the staleness
    guard re-checks it immediately before committing a transfer."""

    node_id: int = -1
    node_rank: int = -1
    rdzv_name: str = ""
    epoch_only: bool = False
    # resharding mode (online re-plan migration): entries list EVERY
    # same-step holder of each shard so the receiver stripes byte
    # ranges across donors in parallel — who sends which shard SLICE to
    # whom when the target sharding differs from the source
    stripe: bool = False


@dataclass
class RestorePlan(Message):
    plan_json: str = ""          # JSON plan dict ("" with epoch_only)
    # world epoch the plan was computed at (bumped on every membership
    # loss): a plan whose epoch no longer matches must not commit
    epoch: int = 0
    step: int = -1
    found: bool = False


@dataclass
class ShardPlanRequest(Message):
    """A worker (or tool) asking for the current parallelism plan for
    its world (parallel/planner.py via the rendezvous manager): the
    deterministic mesh + batch shape every rank of the new world must
    agree on. The plan is recomputed from live membership, so a worker
    spawned after the cut sees the cut world's plan."""

    node_id: int = -1
    node_rank: int = -1
    rdzv_name: str = ""


@dataclass
class ShardPlanResult(Message):
    plan_json: str = ""          # JSON plan dict ("" = no plan)
    # world epoch the plan was computed at (same staleness discipline
    # as RestorePlan: a membership loss after computation bumps it)
    epoch: int = 0
    generation: int = 0
    found: bool = False


@dataclass
class SliceStatusRequest(Message):
    """A worker's cross-slice gradient sync asking which slices are
    currently formed (parallel/dcn_sync.py): the PRESENT set the
    degraded-mode renormalization divides by."""

    node_id: int = -1
    node_rank: int = -1
    rdzv_name: str = ""


@dataclass
class SliceStatus(Message):
    """JSON {"total": n, "fleet_step": s, "slices": {sid: {"formed":
    bool, "ranks": [...], "generation": g, "draining": bool}}} — the
    master's slice registry view plus the job step high-water mark
    (the re-formed slice's catch-up target)."""

    status_json: str = ""


@dataclass
class NetworkStatusReport(Message):
    node_id: int = -1
    normal: bool = True
    elapsed_time: float = 0.0


@dataclass
class NetworkCheckResultRequest(Message):
    node_id: int = -1


@dataclass
class NetworkCheckVerdict(Message):
    normal: bool = True
    is_straggler: bool = False
    reason: str = ""


# --------------------------------------------------------------------------
# KV store (reference: KeyValuePair)
# --------------------------------------------------------------------------


@dataclass
class KeyValuePair(Message):
    key: str = ""
    value: bytes = b""


@dataclass
class KVGetRequest(Message):
    key: str = ""


@dataclass
class KVAddRequest(Message):
    key: str = ""
    amount: int = 0


@dataclass
class KVWaitRequest(Message):
    """Server-side blocking wait on keys (bounded by the RPC deadline)."""

    keys: List[str] = field(default_factory=list)
    timeout_s: float = 10.0


@dataclass
class KVIntResult(Message):
    value: int = 0


# --------------------------------------------------------------------------
# Node health / lifecycle (reference: NodeFailure, GPUStats …)
# --------------------------------------------------------------------------


@dataclass
class NodeFailureReport(Message):
    node_id: int = -1
    node_rank: int = -1
    error_data: str = ""
    level: str = ""              # TrainingMsgLevel.*
    restart_count: int = 0
    # NodeExitReason.* classification of the worker exit (WorkerExit.
    # classify): the diagnosis layer must tell hang from crash from
    # drain. "" = sender predates the field.
    exit_kind: str = ""


@dataclass
class DrainReport(Message):
    """The advance-notice drain protocol (agent → master).

    phase="notice": this node received a preemption notice and is
    draining — it will emergency-checkpoint and depart by ``deadline``
    (unix ts). The master marks the rank DRAINING, fans out urgent
    ``checkpoint`` actions and pre-plans the post-departure world.

    phase="complete": the worker exited with the clean-drain code; the
    master removes the rank NOW (planned departure) so survivors re-form
    in one round instead of waiting out the liveness timeout."""

    node_id: int = -1
    node_rank: int = -1
    deadline: float = 0.0        # unix ts the VM disappears at
    reason: str = ""             # notice source / chaos tag
    phase: str = "notice"        # "notice" | "complete"


@dataclass
class DrainResult(Message):
    success: bool = True
    # ranks the master queued urgent checkpoint actions for (phase=
    # notice): lets the draining agent log the blast radius
    checkpoint_ranks: List[int] = field(default_factory=list)


@dataclass
class ChipStats(Message):
    index: int = 0
    # < 0 = unknown (the exporter derives a duty-cycle proxy only when
    # it has consecutive samples to derive it FROM; 0.0 would be a lie)
    duty_cycle_pct: float = -1.0
    hbm_used_mb: float = 0.0
    hbm_total_mb: float = 0.0
    # allocator peak high-water mark (memory_stats peak_bytes_in_use,
    # obs/device.py): the IN-step transient the between-steps
    # bytes_in_use sample misses — what HbmPressureRule judges. < 0 =
    # unknown / sender predates the field.
    hbm_peak_mb: float = -1.0


@dataclass
class NodeResourceStats(Message):
    node_id: int = -1
    node_type: str = ""
    cpu_percent: float = 0.0
    memory_mb: float = 0.0
    # rendezvous rank (see NodeHeartbeat.node_rank): the diagnosis
    # engine keys all per-worker evidence by rank — node_id diverges
    # from rank after a relaunch. -1 = sender predates the field.
    node_rank: int = -1
    chip_stats: List[ChipStats] = field(default_factory=list)


@dataclass
class NodeHeartbeat(Message):
    node_id: int = -1
    node_type: str = ""
    timestamp: float = 0.0
    # rendezvous liveness is keyed by RANK (node_id diverges from rank
    # after a relaunch, run.py); -1 = sender predates the field
    node_rank: int = -1


@dataclass
class NodeAddressReport(Message):
    node_id: int = -1
    node_rank: int = -1
    addr: str = ""


@dataclass
class GlobalStepReport(Message):
    node_id: int = -1
    step: int = 0
    timestamp: float = 0.0
    node_rank: int = -1        # see NodeHeartbeat.node_rank
    # per-worker speed evidence for the diagnosis engine: mean wall time
    # per step and mean data-wait fraction over the sender's report
    # window (from the worker's phase timeline, obs/timeline.py).
    # 0.0 / -1.0 = sender predates the fields or has no timeline.
    step_time_s: float = 0.0
    data_wait_fraction: float = -1.0
    # achieved-vs-peak model-FLOPs utilization over the sender's report
    # window (obs/mfu.py; needs the worker's FLOPs model + peak). -1.0 =
    # sender predates the field or has no FLOPs model — the collapse
    # rule then falls back to raw steps/s.
    mfu: float = -1.0
    # steps in this report window the sender's slice took in DEGRADED
    # mode (gradient mean renormalized over present slices while a peer
    # slice was absent, parallel/dcn_sync.py). 0 = none / predates.
    degraded_steps: int = 0
    # device-truth HBM peak watermark over the report window
    # (obs/device.py: jax memory_stats peak-bytes — the transient
    # IN-step peak, not the between-steps trough). 0 = backend has no
    # memory stats (CPU) / sender predates the field.
    hbm_peak_bytes: float = 0.0
    # the generation of the shard plan the sender's loop ACTUALLY
    # applied (parallel/calibration.py attributes the timing evidence
    # by this, so an old incarnation's straggling report can never
    # land on a shape it did not run). >= 0 = a stamped plan's
    # generation; -1 = sender predates the field (the master falls
    # back to current-signature attribution); -2 = sender is running
    # the replan FALLBACK mesh (not the stamped plan — dropped).
    plan_generation: int = -1


@dataclass
class ModelInfo(Message):
    """Static model stats fed to the resource optimizer (reference:
    common/grpc.py ModelInfo; profile_extractor)."""

    param_count: int = 0
    param_bytes: int = 0
    flops_per_step: float = 0.0
    # the CONFIGURED global batch (the planner's requested baseline: a
    # re-plan that shrank the batch must not ratchet the profile down
    # — a later grow should restore the full batch)
    batch_size: int = 0
    seq_len: int = 0
    # the batch actually trained per step right now (re-plan adjusted;
    # 0 = same as batch_size) — what tokens/s gauges scale by
    effective_global_batch: int = 0
    # model-FLOPs accounting (obs/mfu.py): FLOPs per trained token
    # (fwd+bwd, causal-discounted attention term), the sender's per-chip
    # bf16 peak, and the global chip count its mesh spans — the master's
    # MFU gauges are tokens/s × flops_per_token / (peak × chips).
    # 0 = sender predates the fields.
    flops_per_token: float = 0.0
    peak_flops_per_chip: float = 0.0
    chips: int = 0
    # "analytic" (6·params formula) or "cost_analysis" (cross-checked
    # against the compiled step's XLA cost analysis)
    flops_source: str = ""
    # model-dim divisibility granules for the parallelism planner
    # (parallel/planner.py): a tensor axis is only feasible when it
    # divides tensor_divisor (gcd of heads/kv-heads/mlp/vocab dims),
    # an fsdp axis when it divides fsdp_divisor (the embed dim). 0 =
    # unknown — the planner then relies on the worker-side trace probe
    # + loud fallback.
    tensor_divisor: int = 0
    fsdp_divisor: int = 0


# --------------------------------------------------------------------------
# Elastic / scaling control (reference: ParallelConfig, ScalePlan relay)
# --------------------------------------------------------------------------


@dataclass
class ParallelConfig(Message):
    """Master-tuned runtime knobs the worker hot-reloads (reference:
    paral_config_tuner.py + ElasticDataLoader hot-reload)."""

    dataloader_batch_size: int = 0
    dataloader_workers: int = 0
    learning_rate: float = 0.0
    grad_accum_steps: int = 0
    version: int = 0


@dataclass
class ParallelConfigRequest(Message):
    node_id: int = -1


@dataclass
class ScaleRequest(Message):
    """Manual/auto scale plan relayed to the master (reference: ScalePlan CRD)."""

    node_type: str = ""
    count: int = 0
    cpu: float = 0.0
    memory_mb: float = 0.0


@dataclass
class JobStatusRequest(Message):
    pass


@dataclass
class JobStatus(Message):
    stage: str = ""
    exit_reason: str = ""


@dataclass
class SyncJoinRequest(Message):
    """Named barrier join (reference: sync_service.py)."""

    sync_name: str = ""
    node_id: int = -1


@dataclass
class SyncFinishRequest(Message):
    sync_name: str = ""


@dataclass
class SyncQueryRequest(Message):
    sync_name: str = ""


@dataclass
class ClusterVersionRequest(Message):
    """PS-style cluster version arbitration (reference: elastic_ps.py)."""

    task_type: str = ""
    task_id: int = 0
    version_type: str = ""       # "local" | "global" | "restored"
    version: int = 0


@dataclass
class ClusterVersion(Message):
    version: int = 0


# --------------------------------------------------------------------------
# Telemetry (obs/): agent/worker → master metrics + spans
# --------------------------------------------------------------------------


@dataclass
class MetricSample(Message):
    """One registry operation to replay on the master's registry."""

    kind: str = "gauge"          # "counter" (inc) | "gauge" (set) |
    #                              "histogram" (observe)
    name: str = ""
    value: float = 0.0
    labels: Dict[str, str] = field(default_factory=dict)


@dataclass
class TelemetryReport(Message):
    """Batched metric samples + finished spans from a node (obs/).

    Spans ride as JSON (list of span dicts, `Span.to_dict`) so the
    payload stays allowlist-friendly and schema-stable across versions.
    """

    node_id: int = -1
    node_rank: int = -1
    node_type: str = ""
    samples: List[MetricSample] = field(default_factory=list)
    spans_json: str = ""
    # batched per-step trace records (obs/steptrace.py record dicts),
    # same JSON-in-string convention as spans_json; "" = none
    steptrace_json: str = ""


# --------------------------------------------------------------------------
# Training diagnosis (master/diagnosis/): reports + the action grammar
# --------------------------------------------------------------------------


@dataclass
class DiagnosisActionRequest(Message):
    """An agent polling for actions the diagnosis engine addressed to its
    rank (observe / profile:{rank} / restart:{rank} / alert)."""

    node_id: int = -1
    node_rank: int = -1


@dataclass
class DiagnosisActions(Message):
    """Actions popped for the polling rank. JSON list of action dicts
    ({"id", "kind", "rank", "reason", ...}) — allowlist-friendly and
    schema-stable across versions, like TelemetryReport.spans_json."""

    actions_json: str = ""


@dataclass
class DiagnosisReportRequest(Message):
    """tools/diagnose.py asking a live master for recent reports
    (limit = 0 → everything retained)."""

    limit: int = 0


@dataclass
class DiagnosisReports(Message):
    reports_json: str = ""       # JSON list of DiagnosisReport dicts


@dataclass
class TimeSeriesQuery(Message):
    """tools/top.py (or any scraper) asking the master's time-series
    store (obs/tsdb.py) for windowed, aligned history. ``name`` may end
    with ``*`` for a prefix match; "" lists available series names.
    ``labels`` is a subset filter; ``resolution_s`` 0 = auto (raw when
    it covers the window, else the finest covering tier)."""

    name: str = ""
    labels: Dict[str, str] = field(default_factory=dict)
    window_s: float = 0.0
    resolution_s: float = 0.0


@dataclass
class TimeSeriesResult(Message):
    """JSON TimeSeriesStore.query_payload dict: {"series": [...],
    "tiers": [...], "stats": {...}} (or {"names": [...]} for a listing).
    "" = master has no time-series store."""

    result_json: str = ""


@dataclass
class PlanCalibrationRequest(Message):
    """The planner calibration table (parallel/calibration.py):
    predicted vs measured step time / MFU per applied shard-plan
    signature, plus the learned per-axis discounts."""

    pass


@dataclass
class PlanCalibrationReport(Message):
    report_json: str = ""        # JSON {"table": [...], "discounts": {}}


@dataclass
class GoodputRequest(Message):
    """tools/goodput.py asking a live master for the goodput ledger
    (window_s > 0 additionally returns a trailing-window summary)."""

    window_s: float = 0.0


@dataclass
class GoodputReport(Message):
    report_json: str = ""        # JSON GoodputLedger.snapshot() dict


@dataclass
class ClockProbe(Message):
    """One NTP-style clock probe (obs/steptrace.py ClockSync): the
    worker wraps this round trip in local wall-clock reads and estimates
    its offset against the master from the midpoint. The servicer
    answers immediately with its wall clock — no locks, no state — so
    the RTT (the uncertainty bound) stays honest."""

    node_id: int = -1


@dataclass
class ClockProbeResult(Message):
    server_ts: float = 0.0       # master wall clock; <= 0 = unsupported


@dataclass
class StepTraceRequest(Message):
    """tools/steptrace.py (or top.py) asking the master's
    StepTraceAssembler for assembled per-step critical paths.
    ``start_step``/``end_step`` bound the range inclusively (-1 = open);
    ``last_n`` > 0 instead returns the newest N solved steps."""

    start_step: int = -1
    end_step: int = -1
    last_n: int = 0


@dataclass
class StepTraceResult(Message):
    """JSON StepTraceAssembler.query_payload dict ({"version", "steps",
    "summary"}). "" = master has no assembler (predates steptrace)."""

    result_json: str = ""


@dataclass
class AutoscaleStatusRequest(Message):
    """tools/diagnose.py (or top.py) asking a live master for the fleet
    controller's decision history + guardrail state
    (brain/fleet_controller.py FleetController.status())."""

    pass


@dataclass
class AutoscaleStatus(Message):
    """JSON FleetController.status() dict ({"decisions", "watch",
    "quarantine", "offers", ...}). "" = controller disabled
    (fleet_controller_enabled off) or master predates it."""

    status_json: str = ""


# --------------------------------------------------------------------------
# Brain service (reference: dlrover/proto/brain.proto persist_metrics /
# optimize / get_job_metrics; dlrover/python/brain/client.py)
# --------------------------------------------------------------------------


@dataclass
class BrainMetricsReport(Message):
    """persist_metrics: one record of job runtime/meta/model metrics."""

    job_name: str = ""
    job_uuid: str = ""
    record_type: str = ""        # "job_meta" | "runtime" | "model" | "job_exit"
    payload_json: str = ""


@dataclass
class BrainOptimizeRequest(Message):
    """optimize: ask for a resource plan at a given stage."""

    job_name: str = ""
    stage: str = ""              # OptimizeStage.*
    config_json: str = ""


@dataclass
class BrainResourcePlan(Message):
    plan_json: str = ""          # {"node_group_resources": {type: {...}}}
    found: bool = False


@dataclass
class BrainJobMetricsRequest(Message):
    """get_job_metrics: fetch persisted records of a job."""

    job_name: str = ""
    record_type: str = ""


@dataclass
class BrainJobMetrics(Message):
    records_json: str = ""       # JSON list


# --------------------------------------------------------------------------
# Coworker data plane (reference: atorch/service/coworker_data_service.py,
# data_info_service.py, protos/coworker.proto)
# --------------------------------------------------------------------------


@dataclass
class CoworkerBatch(Message):
    """One preprocessed batch pushed from a CPU coworker pod."""

    dataset_name: str = ""
    payload: bytes = b""         # pickled batch (numpy trees)
    producer_id: int = -1
    seq: int = -1


@dataclass
class CoworkerBatchRequest(Message):
    dataset_name: str = ""


@dataclass
class CoworkerInfo(Message):
    """Queue depth/capacity so producers can back off (data_info_service)."""

    dataset_name: str = ""
    queued: int = 0
    capacity: int = 0
    finished: bool = False
