"""Version-compat shims for newer-jax APIs the codebase targets.

The kernels and manual-sharding paths are written against current jax
(`jax.shard_map` with ``axis_names``/``check_vma``, `jax.typeof`,
``ShapeDtypeStruct(vma=...)``, ``pltpu.CompilerParams``). Older runtimes
(e.g. 0.4.x) spell these differently or lack them entirely; importing a
kernel module must not fail there — collection of the whole test suite
rides on it. Every shim degrades to the old API's semantics:

- ``shard_map(...)``: translates ``axis_names`` → the old ``auto``
  complement and ``check_vma`` → ``check_rep`` when the new entry point
  is missing.
- ``typeof(x)`` / ``get_vma(x)``: `jax.typeof` when present, else the
  abstract value via ``jax.api_util.shaped_abstractify``; ``get_vma``
  returns the varying-manual-axes set, or ``frozenset()`` on runtimes
  that have no vma tracking (their shard_map does not require outputs
  to declare it).
- ``shape_dtype_struct(...)``: drops the ``vma`` kwarg when
  ``ShapeDtypeStruct`` does not accept it.
- ``tpu_compiler_params(...)``: `pltpu.CompilerParams` or the older
  ``TPUCompilerParams`` spelling.
"""

from __future__ import annotations

import inspect
from typing import Any, Optional

import jax

# --------------------------------------------------------------------------
# shard_map
# --------------------------------------------------------------------------

_NEW_SHARD_MAP = getattr(jax, "shard_map", None)
if _NEW_SHARD_MAP is None:
    from jax.experimental.shard_map import shard_map as _OLD_SHARD_MAP
else:
    _OLD_SHARD_MAP = None

# Partial-auto shard_map (manual over a subset of a multi-axis mesh) is
# only reliable on the new entry point: the old experimental `auto=`
# translation either raises NotImplementedError at trace time or — worse —
# aborts the process inside XLA's CPU backend on some programs.
HAS_PARTIAL_AUTO = _NEW_SHARD_MAP is not None

# Coarse old-runtime marker: tests whose tolerances/assertions are tuned
# to the modern XLA SPMD partitioner (collective reduction order, the
# involuntary-remat eliminations) skip on runtimes that predate it.
LEGACY_JAX = _NEW_SHARD_MAP is None


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              axis_names: Optional[frozenset] = None,
              check_vma: Optional[bool] = None):
    """`jax.shard_map` across jax versions.

    axis_names: the MANUAL axes (new-API meaning). On the old API this
    becomes ``auto = mesh.axis_names - axis_names``. check_vma maps to
    the old ``check_rep``.
    """
    if _NEW_SHARD_MAP is not None:
        kwargs: dict = {}
        if axis_names is not None:
            kwargs["axis_names"] = axis_names
        if check_vma is not None:
            kwargs["check_vma"] = check_vma
        return _NEW_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, **kwargs)
    kwargs = {}
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        # Size-1 auto axes can be made manual instead (a single shard IS
        # the whole array; no spec mentions them) — only a real (>1)
        # auto axis needs partial-auto support.
        if any(mesh.shape[a] > 1 for a in auto):
            # Raise HERE (catchable) rather than let the old partial-auto
            # path abort the process inside the XLA CPU backend.
            raise NotImplementedError(
                "partial-auto shard_map (manual over "
                f"{sorted(axis_names)} of {sorted(mesh.axis_names)}) "
                "requires a jax with jax.shard_map")
        # size-1-manual axes would trip the replication checker; honor an
        # explicit check_vma=True, default off otherwise
        kwargs["check_rep"] = (check_vma if check_vma is not None
                               else False)
    elif check_vma is not None:
        kwargs["check_rep"] = check_vma
    return _OLD_SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kwargs)


# --------------------------------------------------------------------------
# typeof / vma
# --------------------------------------------------------------------------

_TYPEOF = getattr(jax, "typeof", None)


def typeof(x) -> Any:
    """Abstract value of ``x`` (`jax.typeof` when available)."""
    if _TYPEOF is not None:
        return _TYPEOF(x)
    from jax.api_util import shaped_abstractify

    return shaped_abstractify(x)


def get_vma(x) -> frozenset:
    """Varying-manual-axes of ``x``; empty on runtimes without vma."""
    return frozenset(getattr(typeof(x), "vma", frozenset()) or frozenset())


HAS_VMA = "vma" in inspect.signature(jax.ShapeDtypeStruct.__init__).parameters


def shape_dtype_struct(shape, dtype, vma: frozenset = frozenset(),
                       sharding=None) -> jax.ShapeDtypeStruct:
    """``ShapeDtypeStruct`` carrying ``vma`` only where supported."""
    kwargs: dict = {}
    if sharding is not None:
        kwargs["sharding"] = sharding
    if HAS_VMA:
        kwargs["vma"] = vma
    return jax.ShapeDtypeStruct(shape, dtype, **kwargs)


# --------------------------------------------------------------------------
# host memory kinds (optimizer-state offload)
# --------------------------------------------------------------------------


def host_memory_kind(device=None) -> str:
    """The host memory kind this backend can address ("pinned_host" on
    TPU and modern CPU backends; older CPU backends only expose
    "unpinned_host" — offloading there still exercises the lowering)."""
    device = device if device is not None else jax.devices()[0]
    try:
        kinds = {m.kind for m in device.addressable_memories()}
    except Exception:  # noqa: BLE001 — probing only; default optimistically
        return "pinned_host"
    if "pinned_host" in kinds:
        return "pinned_host"
    for kind in sorted(kinds):
        if kind.endswith("host"):
            return kind
    return "pinned_host"


# --------------------------------------------------------------------------
# pallas TPU compiler params
# --------------------------------------------------------------------------


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` / legacy ``TPUCompilerParams``."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or getattr(
        pltpu, "TPUCompilerParams")
    return cls(**kwargs)
