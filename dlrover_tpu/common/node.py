"""Node model and status state machine.

Capability parity: dlrover/python/common/node.py (Node/NodeResource/
NodeGroupResource) and dlrover/python/master/node/status_flow.py
(NODE_STATE_FLOWS, relaunch decisions). Resources speak TPU: a node is a TPU
host with `chips` attached chips of `chip_type` instead of GPU cards.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from dlrover_tpu.common.constants import NodeExitReason, NodeStatus


@dataclass
class NodeResource:
    """Requested/used resources of one node (TPU host)."""

    cpu: float = 0.0
    memory_mb: float = 0.0
    chips: int = 0               # TPU chips attached to this host
    chip_type: str = ""          # e.g. "v5p", "v5e"
    priority: str = ""

    def to_dict(self):
        return {
            "cpu": self.cpu,
            "memory_mb": self.memory_mb,
            "chips": self.chips,
            "chip_type": self.chip_type,
            "priority": self.priority,
        }

    @classmethod
    def from_dict(cls, d):
        return cls(**d) if d else cls()


@dataclass
class NodeGroupResource:
    """Resource config of a node group (count × per-node resource)."""

    count: int = 0
    node_resource: NodeResource = field(default_factory=NodeResource)

    def update(self, count: Optional[int] = None,
               cpu: Optional[float] = None,
               memory_mb: Optional[float] = None):
        if count is not None and count > 0:
            self.count = count
        if cpu is not None and cpu > 0:
            self.node_resource.cpu = cpu
        if memory_mb is not None and memory_mb > 0:
            self.node_resource.memory_mb = memory_mb


class Node:
    """One training node (TPU host) as seen by the master."""

    def __init__(
        self,
        node_type: str,
        node_id: int,
        rank_index: Optional[int] = None,
        name: str = "",
        status: str = NodeStatus.INITIAL,
        config_resource: Optional[NodeResource] = None,
        critical: bool = False,
        max_relaunch_count: int = 3,
        relaunchable: bool = True,
    ):
        self.type = node_type
        self.id = node_id
        self.rank_index = rank_index if rank_index is not None else node_id
        self.name = name or f"{node_type}-{node_id}"
        self.status = status
        self.config_resource = config_resource or NodeResource()
        self.used_resource = NodeResource()
        self.critical = critical
        self.relaunchable = relaunchable
        self.max_relaunch_count = max_relaunch_count
        self.relaunch_count = 0
        self.exit_reason = ""
        self.host_addr = ""
        self.host_port = 0
        self.create_time: Optional[float] = None
        self.start_time: Optional[float] = None
        self.finish_time: Optional[float] = None
        self.heartbeat_time: float = 0.0
        self.is_released = False
        self.paral_config = None
        self.start_hang_time: float = 0.0

    # -- status transitions ------------------------------------------------
    def update_status(self, status: str) -> None:
        self.status = status
        now = time.time()
        if status == NodeStatus.RUNNING and self.start_time is None:
            self.start_time = now
        if status in NodeStatus.terminal() and self.finish_time is None:
            self.finish_time = now

    def is_unrecoverable_failure(self) -> bool:
        if self.relaunch_count >= self.max_relaunch_count:
            return True
        if self.exit_reason == NodeExitReason.FATAL_ERROR:
            return True
        return False

    def is_alive(self) -> bool:
        return self.status in (NodeStatus.PENDING, NodeStatus.RUNNING,
                               NodeStatus.INITIAL)

    def get_relaunch_node(self, new_id: int) -> "Node":
        """Build the replacement node after this one fails (reference:
        dist_job_manager relaunch path)."""
        node = Node(
            self.type,
            new_id,
            rank_index=self.rank_index,
            status=NodeStatus.INITIAL,
            config_resource=self.config_resource,
            critical=self.critical,
            max_relaunch_count=self.max_relaunch_count,
        )
        node.relaunch_count = self.relaunch_count + 1
        return node

    # -- crash-consistent state (master/state_backend.py) ------------------
    def to_dict(self) -> dict:
        return {
            "type": self.type,
            "id": self.id,
            "rank_index": self.rank_index,
            "name": self.name,
            "status": self.status,
            "config_resource": self.config_resource.to_dict(),
            "critical": self.critical,
            "relaunchable": self.relaunchable,
            "max_relaunch_count": self.max_relaunch_count,
            "relaunch_count": self.relaunch_count,
            "exit_reason": self.exit_reason,
            "host_addr": self.host_addr,
            "host_port": self.host_port,
            "create_time": self.create_time,
            "start_time": self.start_time,
            "finish_time": self.finish_time,
            "is_released": self.is_released,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Node":
        node = cls(
            d["type"],
            int(d["id"]),
            rank_index=int(d.get("rank_index", d["id"])),
            name=d.get("name", ""),
            status=d.get("status", NodeStatus.INITIAL),
            config_resource=NodeResource.from_dict(
                d.get("config_resource")),
            critical=bool(d.get("critical", False)),
            max_relaunch_count=int(d.get("max_relaunch_count", 3)),
            relaunchable=bool(d.get("relaunchable", True)),
        )
        node.relaunch_count = int(d.get("relaunch_count", 0))
        node.exit_reason = d.get("exit_reason", "")
        node.host_addr = d.get("host_addr", "")
        node.host_port = int(d.get("host_port", 0))
        node.create_time = d.get("create_time")
        node.start_time = d.get("start_time")
        node.finish_time = d.get("finish_time")
        node.is_released = bool(d.get("is_released", False))
        return node

    def __repr__(self):
        return (f"Node({self.type}-{self.id} rank={self.rank_index} "
                f"status={self.status})")


@dataclass
class NodeStateFlow:
    from_status: str
    to_status: str
    event_type: str
    should_relaunch: bool = False


# Allowed transitions (reference: status_flow.py NODE_STATE_FLOWS). "*" is a
# wildcard from-state; relaunch decisions additionally consult exit_reason in
# the node manager.
_ANY = "*"

NODE_STATE_FLOWS = [
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.PENDING, "added"),
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.RUNNING, "modified"),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.RUNNING, "modified"),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.SUCCEEDED, "modified"),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.FAILED, "modified",
                  should_relaunch=True),
    NodeStateFlow(NodeStatus.PENDING, NodeStatus.DELETED, "deleted",
                  should_relaunch=True),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.SUCCEEDED, "modified"),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.FAILED, "modified",
                  should_relaunch=True),
    NodeStateFlow(NodeStatus.RUNNING, NodeStatus.DELETED, "deleted",
                  should_relaunch=True),
    NodeStateFlow(NodeStatus.INITIAL, NodeStatus.DELETED, "deleted"),
    NodeStateFlow(_ANY, NodeStatus.BREAKDOWN, "modified",
                  should_relaunch=True),
    NodeStateFlow(NodeStatus.SUCCEEDED, NodeStatus.DELETED, "deleted"),
    NodeStateFlow(NodeStatus.FAILED, NodeStatus.DELETED, "deleted"),
]


def get_node_state_flow(from_status: str, event_type: str,
                        to_status: str) -> Optional[NodeStateFlow]:
    """Look up the allowed transition, or None if the event is stale/invalid."""
    if from_status == to_status:
        return None
    for flow in NODE_STATE_FLOWS:
        if (flow.from_status in (from_status, _ANY)
                and flow.to_status == to_status
                and flow.event_type == event_type):
            return flow
    # A deletion always applies regardless of recorded state.
    if event_type == "deleted" and to_status == NodeStatus.DELETED:
        relaunch = from_status not in NodeStatus.terminal()
        return NodeStateFlow(from_status, to_status, event_type, relaunch)
    return None
