"""Global context singleton of runtime tunables.

Capability parity: dlrover/python/common/global_context.py — one place for
timeouts, thresholds and ports, overridable via env vars (``DLROVER_TPU_<KEY>``)
or programmatically (tests), and updatable at runtime from a resource-plan
service (the Brain-equivalent) without restarting the master.
"""

from __future__ import annotations

import os
import threading

from dlrover_tpu.common.constants import DefaultValues


class Context:
    _instance = None
    _lock = threading.Lock()

    def __init__(self):
        self.master_port: int = DefaultValues.MASTER_PORT
        self.metrics_port: int = DefaultValues.METRICS_PORT
        self.rdzv_timeout_s: float = DefaultValues.RDZV_TIMEOUT_S
        self.rdzv_wait_new_node_s: float = DefaultValues.RDZV_WAIT_NEW_NODE_S
        self.task_timeout_s: float = DefaultValues.TASK_TIMEOUT_S
        self.heartbeat_interval_s: float = DefaultValues.HEARTBEAT_INTERVAL_S
        self.hang_seconds: float = DefaultValues.HANG_SECONDS
        self.dead_node_timeout_s: float = (
            DefaultValues.DEAD_NODE_TIMEOUT_S
        )
        self.max_relaunch: int = DefaultValues.MAX_RELAUNCH
        self.kv_wait_timeout_s: float = DefaultValues.KV_WAIT_TIMEOUT_S
        # client RPC budget (agent/master_client.py): per-call deadline,
        # attempt count, and the jittered-exponential-backoff envelope —
        # tests shrink these so failure paths run in milliseconds
        self.rpc_timeout_s: float = DefaultValues.RPC_TIMEOUT_S
        self.rpc_retries: int = DefaultValues.RPC_RETRIES
        self.rpc_backoff_s: float = DefaultValues.RPC_BACKOFF_S
        self.rpc_backoff_max_s: float = DefaultValues.RPC_BACKOFF_MAX_S
        self.master_reconnect_timeout_s: float = (
            DefaultValues.MASTER_RECONNECT_TIMEOUT_S
        )
        # crash-consistent master state: snapshots land here ("" = state
        # persistence disabled); the bootstrap file carries the master's
        # advertised address across restarts ("" = env-only resolution)
        self.master_state_dir: str = ""
        self.master_bootstrap_file: str = ""
        self.master_snapshot_retain: int = (
            DefaultValues.MASTER_SNAPSHOT_RETAIN
        )
        self.master_snapshot_min_interval_s: float = (
            DefaultValues.MASTER_SNAPSHOT_MIN_INTERVAL_S
        )
        # sharded control plane (master/rendezvous_shards.py +
        # master/coord_service.py + master/standby.py): per-slice
        # rendezvous shards, the KV/coordination tier's own port, the
        # bounded telemetry ingest, and the hot-standby promoter
        self.rdzv_sharded: bool = DefaultValues.RDZV_SHARDED
        self.coord_port: int = DefaultValues.COORD_PORT
        self.telemetry_queue_size: int = (
            DefaultValues.TELEMETRY_QUEUE_SIZE
        )
        self.kv_gc_keep_generations: int = (
            DefaultValues.KV_GC_KEEP_GENERATIONS
        )
        self.standby_health_interval_s: float = (
            DefaultValues.STANDBY_HEALTH_INTERVAL_S
        )
        self.standby_promote_failures: int = (
            DefaultValues.STANDBY_PROMOTE_FAILURES
        )
        self.monitor_interval_s: float = DefaultValues.MONITOR_INTERVAL_S
        self.report_resource_interval_s: float = (
            DefaultValues.REPORT_RESOURCE_INTERVAL_S
        )
        self.speed_sample_window: int = DefaultValues.SPEED_SAMPLE_WINDOW
        self.straggler_median_ratio: float = (
            DefaultValues.STRAGGLER_MEDIAN_RATIO
        )
        # training diagnosis engine (master/diagnosis/): rule thresholds,
        # cadence and the action kill-switch — see docs/observability.md
        self.diagnosis_enabled: bool = DefaultValues.DIAGNOSIS_ENABLED
        self.diagnosis_interval_s: float = (
            DefaultValues.DIAGNOSIS_INTERVAL_S
        )
        self.diagnosis_worker_window: int = (
            DefaultValues.DIAGNOSIS_WORKER_WINDOW
        )
        self.diagnosis_min_worker_samples: int = (
            DefaultValues.DIAGNOSIS_MIN_WORKER_SAMPLES
        )
        self.straggler_trigger_windows: int = (
            DefaultValues.STRAGGLER_TRIGGER_WINDOWS
        )
        self.straggler_clear_windows: int = (
            DefaultValues.STRAGGLER_CLEAR_WINDOWS
        )
        self.diagnosis_data_wait_fraction: float = (
            DefaultValues.DIAGNOSIS_DATA_WAIT_FRACTION
        )
        self.diagnosis_hbm_pressure_pct: float = (
            DefaultValues.DIAGNOSIS_HBM_PRESSURE_PCT
        )
        self.diagnosis_collapse_ratio: float = (
            DefaultValues.DIAGNOSIS_COLLAPSE_RATIO
        )
        self.diagnosis_actions_enabled: bool = (
            DefaultValues.DIAGNOSIS_ACTIONS_ENABLED
        )
        self.diagnosis_profile_steps: int = (
            DefaultValues.DIAGNOSIS_PROFILE_STEPS
        )
        self.diagnosis_action_cooldown_s: float = (
            DefaultValues.DIAGNOSIS_ACTION_COOLDOWN_S
        )
        # goodput ledger alerting (obs/goodput.py, GoodputRule):
        # threshold 0 = disabled
        self.goodput_alert_threshold: float = (
            DefaultValues.GOODPUT_ALERT_THRESHOLD
        )
        self.goodput_window_s: float = DefaultValues.GOODPUT_WINDOW_S
        self.goodput_min_coverage: float = (
            DefaultValues.GOODPUT_MIN_COVERAGE
        )
        # fleet time-series plane (obs/tsdb.py): master-side history
        # store sampling + sidecar-persistence cadences
        self.tsdb_sample_interval_s: float = (
            DefaultValues.TSDB_SAMPLE_INTERVAL_S
        )
        self.tsdb_flush_interval_s: float = (
            DefaultValues.TSDB_FLUSH_INTERVAL_S
        )
        # planner calibration (parallel/calibration.py) + the
        # PlanRegressionRule thresholds (master/diagnosis/rules.py)
        self.calibration_min_samples: int = (
            DefaultValues.CALIBRATION_MIN_SAMPLES
        )
        self.plan_regression_ratio: float = (
            DefaultValues.PLAN_REGRESSION_RATIO
        )
        self.plan_regression_windows: int = (
            DefaultValues.PLAN_REGRESSION_WINDOWS
        )
        self.plan_regression_clear_windows: int = (
            DefaultValues.PLAN_REGRESSION_CLEAR_WINDOWS
        )
        self.seconds_per_scale_check: float = (
            DefaultValues.SECONDS_PER_SCALE_CHECK
        )
        # preemption-aware graceful drain (agent/preemption.py) + the
        # deadline-bounded emergency checkpoint (checkpoint/, trainer/)
        self.preempt_default_grace_s: float = (
            DefaultValues.PREEMPT_DEFAULT_GRACE_S
        )
        self.preempt_notice_poll_s: float = (
            DefaultValues.PREEMPT_NOTICE_POLL_S
        )
        self.preempt_env_horizon_s: float = (
            DefaultValues.PREEMPT_ENV_HORIZON_S
        )
        self.emergency_ckpt_min_window_s: float = (
            DefaultValues.EMERGENCY_CKPT_MIN_WINDOW_S
        )
        # peer-to-peer elastic restore (checkpoint/peer_restore.py):
        # replacement ranks restore from surviving hosts' staged state,
        # falling back to Orbax shard-wise when no replica survived
        self.peer_restore_enabled: bool = (
            DefaultValues.PEER_RESTORE_ENABLED
        )
        self.peer_restore_timeout_s: float = (
            DefaultValues.PEER_RESTORE_TIMEOUT_S
        )
        self.peer_donor_port: int = DefaultValues.PEER_DONOR_PORT
        # online parallelism re-planning (parallel/planner.py): the
        # worker builds its mesh + batch/accumulation shape from the
        # master's shard plan; False pins the configured mesh (resizes
        # then only re-form the same DP shape — pre-PR-9 behavior)
        self.replan_enabled: bool = DefaultValues.REPLAN_ENABLED
        # multi-slice hierarchical DP (parallel/dcn_sync.py): degraded-
        # mode budget while a slice is absent, the per-step DCN collect
        # deadline, and the wire quantization of the host-level sync
        self.slice_absent_max_steps: int = (
            DefaultValues.SLICE_ABSENT_MAX_STEPS
        )
        self.dcn_sync_timeout_s: float = DefaultValues.DCN_SYNC_TIMEOUT_S
        self.dcn_sync_poll_s: float = DefaultValues.DCN_SYNC_POLL_S
        self.dcn_sync_quant_bits: int = (
            DefaultValues.DCN_SYNC_QUANT_BITS
        )
        # step-hang watchdog (trainer/watchdog.py); 0 = disabled
        self.hang_watchdog_s: float = DefaultValues.HANG_WATCHDOG_S
        # per-step critical-path tracing (obs/steptrace.py +
        # master/steptrace.py): worker record ring + clock-probe
        # cadence, master assembly ring, and the CriticalPathRule
        # gating-fraction threshold (0 disables the rule)
        self.steptrace_enabled: bool = DefaultValues.STEPTRACE_ENABLED
        self.steptrace_ring: int = DefaultValues.STEPTRACE_RING
        self.steptrace_probe_interval_s: float = (
            DefaultValues.STEPTRACE_PROBE_INTERVAL_S
        )
        self.steptrace_ring_steps: int = (
            DefaultValues.STEPTRACE_RING_STEPS
        )
        self.critical_path_gating_fraction: float = (
            DefaultValues.CRITICAL_PATH_GATING_FRACTION
        )
        # flight-recorder rings (obs/flight_recorder.py): per-process
        # event ring + span-id dedup ring capacities
        self.flight_ring_events: int = DefaultValues.FLIGHT_RING_EVENTS
        self.flight_ring_spans: int = DefaultValues.FLIGHT_RING_SPANS
        # per-rank relaunch backoff + quarantine (agent/elastic_agent.py)
        self.relaunch_backoff_base_s: float = (
            DefaultValues.RELAUNCH_BACKOFF_BASE_S
        )
        self.relaunch_backoff_max_s: float = (
            DefaultValues.RELAUNCH_BACKOFF_MAX_S
        )
        self.quarantine_failures: int = DefaultValues.QUARANTINE_FAILURES
        self.quarantine_window_s: float = (
            DefaultValues.QUARANTINE_WINDOW_S
        )
        # goodput-optimal fleet controller (brain/fleet_controller.py):
        # claim/shed/hold decisions from the measured ledger, guarded by
        # hysteresis + cooldown + rate limit + the rollback watchdog
        self.fleet_controller_enabled: bool = (
            DefaultValues.FLEET_CONTROLLER_ENABLED
        )
        self.autoscale_interval_s: float = (
            DefaultValues.AUTOSCALE_INTERVAL_S
        )
        self.autoscale_cooldown_s: float = (
            DefaultValues.AUTOSCALE_COOLDOWN_S
        )
        self.autoscale_hysteresis_windows: int = (
            DefaultValues.AUTOSCALE_HYSTERESIS_WINDOWS
        )
        self.autoscale_max_decisions_per_hour: int = (
            DefaultValues.AUTOSCALE_MAX_DECISIONS_PER_HOUR
        )
        self.autoscale_rollback_drop_fraction: float = (
            DefaultValues.AUTOSCALE_ROLLBACK_DROP_FRACTION
        )
        self.autoscale_rollback_window_s: float = (
            DefaultValues.AUTOSCALE_ROLLBACK_WINDOW_S
        )
        self.autoscale_quarantine_backoff_s: float = (
            DefaultValues.AUTOSCALE_QUARANTINE_BACKOFF_S
        )
        self.autoscale_claim_margin: float = (
            DefaultValues.AUTOSCALE_CLAIM_MARGIN
        )
        self.autoscale_shed_wait_fraction: float = (
            DefaultValues.AUTOSCALE_SHED_WAIT_FRACTION
        )
        # speed-aware dynamic sharding (master/shard/task_manager.py):
        # False = byte-identical legacy round-robin dispatch
        self.dispatch_speed_weighted: bool = (
            DefaultValues.DISPATCH_SPEED_WEIGHTED
        )
        self.dispatch_weight_floor: float = (
            DefaultValues.DISPATCH_WEIGHT_FLOOR
        )
        # data-pipeline auto-tune (data/prefetch.py): advisory depth /
        # ring sizing from the timeline's data_wait fraction
        self.prefetch_autotune: bool = DefaultValues.PREFETCH_AUTOTUNE
        self.prefetch_depth_min: int = DefaultValues.PREFETCH_DEPTH_MIN
        self.prefetch_depth_max: int = DefaultValues.PREFETCH_DEPTH_MAX
        self.data_wait_tune_fraction: float = (
            DefaultValues.DATA_WAIT_TUNE_FRACTION
        )
        self.relaunch_on_worker_failure: bool = True
        self.auto_scale_enabled: bool = False
        self.network_check_enabled: bool = False
        self._load_env_overrides()

    def _load_env_overrides(self) -> None:
        for name, value in vars(self).items():
            if name.startswith("_"):
                continue
            env_key = f"DLROVER_TPU_{name.upper()}"
            raw = os.getenv(env_key)
            if raw is None:
                continue
            kind = type(value)
            if kind is bool:
                setattr(self, name, raw.lower() in ("1", "true", "yes"))
            else:
                setattr(self, name, kind(raw))

    def update(self, **kwargs) -> None:
        """Runtime override (e.g. from a resource-plan service)."""
        for key, value in kwargs.items():
            if hasattr(self, key) and not key.startswith("_"):
                setattr(self, key, value)

    @classmethod
    def singleton(cls) -> "Context":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        """For tests: drop the singleton so env overrides re-apply."""
        with cls._lock:
            cls._instance = None
