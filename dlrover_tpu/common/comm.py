"""gRPC plumbing for the 2-RPC control plane.

Capability parity: dlrover/python/common/grpc.py (`build_channel` :30, retry
policy :41-48) + dlrover/proto/elastic_training.proto (the 2-method service).
Instead of protoc-generated stubs, the service is registered through gRPC's
generic-handler API with raw-bytes (de)serializers; payloads are the typed
dataclasses of dlrover_tpu.common.messages.
"""

from __future__ import annotations

import json
import socket
from concurrent import futures
from typing import Callable, Optional, Tuple

import grpc

from dlrover_tpu.common.constants import DefaultValues

SERVICE_NAME = "dlrovertpu.Master"
GET_METHOD = f"/{SERVICE_NAME}/get"
REPORT_METHOD = f"/{SERVICE_NAME}/report"

_MAX_MESSAGE_BYTES = DefaultValues.GRPC_MAX_MESSAGE_MB * 1024 * 1024

_RETRY_POLICY = json.dumps({
    "methodConfig": [{
        "name": [{"service": SERVICE_NAME}],
        "retryPolicy": {
            "maxAttempts": 5,
            "initialBackoff": "0.2s",
            "maxBackoff": "3s",
            "backoffMultiplier": 2,
            "retryableStatusCodes": ["UNAVAILABLE"],
        },
    }]
})


def _identity(data: bytes) -> bytes:
    return data


def build_channel(addr: str) -> grpc.Channel:
    options = [
        ("grpc.max_send_message_length", _MAX_MESSAGE_BYTES),
        ("grpc.max_receive_message_length", _MAX_MESSAGE_BYTES),
        ("grpc.enable_retries", 1),
        ("grpc.service_config", _RETRY_POLICY),
    ]
    return grpc.insecure_channel(addr, options=options)


def addr_connectable(addr: str, timeout_s: float = 2.0) -> bool:
    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=timeout_s):
            return True
    except OSError:
        return False


class MasterStub:
    """Client-side stub over the generic channel."""

    def __init__(self, channel: grpc.Channel):
        self._get = channel.unary_unary(
            GET_METHOD, request_serializer=_identity,
            response_deserializer=_identity,
        )
        self._report = channel.unary_unary(
            REPORT_METHOD, request_serializer=_identity,
            response_deserializer=_identity,
        )

    def get(self, payload: bytes, timeout: Optional[float] = None) -> bytes:
        return self._get(payload, timeout=timeout, wait_for_ready=True)

    def report(self, payload: bytes,
               timeout: Optional[float] = None) -> bytes:
        return self._report(payload, timeout=timeout, wait_for_ready=True)


def build_server(
    get_fn: Callable[[bytes, grpc.ServicerContext], bytes],
    report_fn: Callable[[bytes, grpc.ServicerContext], bytes],
    port: int = 0,
    host: str = "0.0.0.0",
    max_workers: int = 64,
) -> Tuple[grpc.Server, int]:
    """Register the 2 methods and bind; returns (server, bound_port)."""
    handlers = {
        "get": grpc.unary_unary_rpc_method_handler(
            get_fn, request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "report": grpc.unary_unary_rpc_method_handler(
            report_fn, request_deserializer=_identity,
            response_serializer=_identity,
        ),
    }
    generic = grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", _MAX_MESSAGE_BYTES),
            ("grpc.max_receive_message_length", _MAX_MESSAGE_BYTES),
        ],
    )
    server.add_generic_rpc_handlers((generic,))
    bound_port = server.add_insecure_port(f"{host}:{port}")
    if bound_port == 0:
        raise RuntimeError(f"cannot bind master port {port}")
    return server, bound_port


def local_ip() -> str:
    """Routable address of this host. gethostbyname(gethostname()) often
    resolves to 127.0.1.1 via /etc/hosts; the UDP-connect trick reads the
    address the kernel would route externally (no packet is sent)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
            sock.connect(("8.8.8.8", 80))
            return sock.getsockname()[0]
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def find_free_port() -> int:
    sock = socket.socket()
    sock.bind(("", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port
