"""gRPC plumbing for the 2-RPC control plane.

Capability parity: dlrover/python/common/grpc.py (`build_channel` :30, retry
policy :41-48) + dlrover/proto/elastic_training.proto (the 2-method service).
Instead of protoc-generated stubs, the service is registered through gRPC's
generic-handler API with raw-bytes (de)serializers; payloads are the typed
dataclasses of dlrover_tpu.common.messages.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import socket
import time
from concurrent import futures
from typing import Callable, Dict, Optional, Tuple

import grpc

from dlrover_tpu.common.constants import DefaultValues

SERVICE_NAME = "dlrovertpu.Master"
GET_METHOD = f"/{SERVICE_NAME}/get"
REPORT_METHOD = f"/{SERVICE_NAME}/report"

# Transport-level fault injection (diagnostics/chaos.py is the step-level
# twin): "drop:0.2;delay:0.5;error:0.05" makes every client RPC drop with
# p=0.2 (raises UNAVAILABLE before the wire), sleep 0.5 s, or fail with
# p=0.05 (INTERNAL) — so retry/reconnect/recovery paths can be exercised
# deterministically (seed via DLROVER_TPU_CHAOS_NET_SEED).
CHAOS_NET_ENV = "DLROVER_TPU_CHAOS_NET"
CHAOS_NET_SEED_ENV = "DLROVER_TPU_CHAOS_NET_SEED"

_MAX_MESSAGE_BYTES = DefaultValues.GRPC_MAX_MESSAGE_MB * 1024 * 1024

_RETRY_POLICY = json.dumps({
    "methodConfig": [{
        "name": [{"service": SERVICE_NAME}],
        "retryPolicy": {
            "maxAttempts": 5,
            "initialBackoff": "0.2s",
            "maxBackoff": "3s",
            "backoffMultiplier": 2,
            "retryableStatusCodes": ["UNAVAILABLE"],
        },
    }]
})


def _identity(data: bytes) -> bytes:
    return data


def build_channel(addr: str) -> grpc.Channel:
    options = [
        ("grpc.max_send_message_length", _MAX_MESSAGE_BYTES),
        ("grpc.max_receive_message_length", _MAX_MESSAGE_BYTES),
        ("grpc.enable_retries", 1),
        ("grpc.service_config", _RETRY_POLICY),
    ]
    return grpc.insecure_channel(addr, options=options)


def addr_connectable(addr: str, timeout_s: float = 2.0) -> bool:
    host, _, port = addr.rpartition(":")
    try:
        with socket.create_connection((host or "127.0.0.1", int(port)),
                                      timeout=timeout_s):
            return True
    except OSError:
        return False


class InjectedRpcError(grpc.RpcError):
    """A client-side fault minted by the transport chaos layer. Shaped
    like a real grpc.RpcError (code()/details()) so retry and error
    classification paths cannot tell it from the genuine article."""

    def __init__(self, code: grpc.StatusCode, details: str):
        super().__init__()
        self._code = code
        self._details = details

    def code(self) -> grpc.StatusCode:
        return self._code

    def details(self) -> str:
        return self._details

    def __str__(self) -> str:
        return f"InjectedRpcError({self._code}, {self._details!r})"


@dataclasses.dataclass
class NetFaultSpec:
    drop: float = 0.0       # P(raise UNAVAILABLE before the wire)
    delay_s: float = 0.0    # added latency when the delay fault fires
    delay_p: float = 1.0    # P(delay fires) when delay_s > 0
    error: float = 0.0      # P(raise INTERNAL before the wire)


def parse_net_chaos(spec: str) -> NetFaultSpec:
    """Parse the CHAOS_NET grammar ("drop:P;delay:S[:P];error:P");
    raises ValueError on a bad spec — a chaos run with a typo'd fault
    must fail loudly, not run clean."""
    result = NetFaultSpec()
    for part in filter(None, (p.strip() for p in spec.split(";"))):
        fields = part.split(":")
        kind = fields[0].strip().lower()
        try:
            if kind == "drop" and len(fields) == 2:
                result.drop = float(fields[1])
            elif kind == "delay" and len(fields) in (2, 3):
                result.delay_s = float(fields[1])
                if len(fields) == 3:
                    result.delay_p = float(fields[2])
            elif kind == "error" and len(fields) == 2:
                result.error = float(fields[1])
            else:
                raise ValueError(f"unknown net fault {kind!r}")
        except ValueError as e:
            raise ValueError(
                f"bad net chaos fault {part!r} (want "
                f"'drop:P', 'delay:S[:P]' or 'error:P'): {e}") from e
    for name, prob in (("drop", result.drop), ("delay", result.delay_p),
                       ("error", result.error)):
        if not 0.0 <= prob <= 1.0:
            raise ValueError(
                f"net chaos {name} probability {prob} outside [0, 1]")
    if result.delay_s < 0:
        raise ValueError(f"net chaos delay {result.delay_s} is negative")
    return result


class TransportFaultInjector:
    """Applies a NetFaultSpec before each client RPC. One instance per
    stub; faults are decided by a private seeded RNG so a chaos run is
    reproducible. Injecting client-side (before gRPC's own channel
    retry policy can see the call) exercises OUR retry layer."""

    def __init__(self, spec: str, seed: Optional[int] = None):
        self._spec = parse_net_chaos(spec)
        self._rng = random.Random(seed)
        self.injected: Dict[str, int] = {"drop": 0, "delay": 0,
                                         "error": 0}

    @classmethod
    def from_env(cls) -> Optional["TransportFaultInjector"]:
        spec = os.environ.get(CHAOS_NET_ENV, "")
        if not spec:
            return None
        seed_raw = os.environ.get(CHAOS_NET_SEED_ENV, "")
        return cls(spec, seed=int(seed_raw) if seed_raw else None)

    def before_rpc(self, method: str) -> None:
        spec = self._spec
        if spec.delay_s > 0 and self._rng.random() < spec.delay_p:
            self.injected["delay"] += 1
            time.sleep(spec.delay_s)
        if spec.drop > 0 and self._rng.random() < spec.drop:
            self.injected["drop"] += 1
            raise InjectedRpcError(
                grpc.StatusCode.UNAVAILABLE,
                f"chaos-net dropped {method}")
        if spec.error > 0 and self._rng.random() < spec.error:
            self.injected["error"] += 1
            raise InjectedRpcError(
                grpc.StatusCode.INTERNAL,
                f"chaos-net errored {method}")


class MasterStub:
    """Client-side stub over the generic channel."""

    def __init__(self, channel: grpc.Channel,
                 fault_injector: Optional[TransportFaultInjector] = None):
        self._get = channel.unary_unary(
            GET_METHOD, request_serializer=_identity,
            response_deserializer=_identity,
        )
        self._report = channel.unary_unary(
            REPORT_METHOD, request_serializer=_identity,
            response_deserializer=_identity,
        )
        # env-armed unless an explicit injector was handed in (tests);
        # None when CHAOS_NET is unset — zero cost on the happy path
        self._fault_injector = (fault_injector
                                if fault_injector is not None
                                else TransportFaultInjector.from_env())

    def get(self, payload: bytes, timeout: Optional[float] = None) -> bytes:
        if self._fault_injector is not None:
            self._fault_injector.before_rpc("get")
        return self._get(payload, timeout=timeout, wait_for_ready=True)

    def report(self, payload: bytes,
               timeout: Optional[float] = None) -> bytes:
        if self._fault_injector is not None:
            self._fault_injector.before_rpc("report")
        return self._report(payload, timeout=timeout, wait_for_ready=True)


def build_server(
    get_fn: Callable[[bytes, grpc.ServicerContext], bytes],
    report_fn: Callable[[bytes, grpc.ServicerContext], bytes],
    port: int = 0,
    host: str = "0.0.0.0",
    max_workers: int = 64,
) -> Tuple[grpc.Server, int]:
    """Register the 2 methods and bind; returns (server, bound_port)."""
    handlers = {
        "get": grpc.unary_unary_rpc_method_handler(
            get_fn, request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "report": grpc.unary_unary_rpc_method_handler(
            report_fn, request_deserializer=_identity,
            response_serializer=_identity,
        ),
    }
    generic = grpc.method_handlers_generic_handler(SERVICE_NAME, handlers)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            ("grpc.max_send_message_length", _MAX_MESSAGE_BYTES),
            ("grpc.max_receive_message_length", _MAX_MESSAGE_BYTES),
        ],
    )
    server.add_generic_rpc_handlers((generic,))
    bound_port = server.add_insecure_port(f"{host}:{port}")
    if bound_port == 0:
        raise RuntimeError(f"cannot bind master port {port}")
    return server, bound_port


def local_ip() -> str:
    """Routable address of this host. gethostbyname(gethostname()) often
    resolves to 127.0.1.1 via /etc/hosts; the UDP-connect trick reads the
    address the kernel would route externally (no packet is sent)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sock:
            sock.connect(("8.8.8.8", 80))
            return sock.getsockname()[0]
    except OSError:
        try:
            return socket.gethostbyname(socket.gethostname())
        except OSError:
            return "127.0.0.1"


def find_free_port() -> int:
    sock = socket.socket()
    sock.bind(("", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port
