"""Shared process-set bootstrap helpers (agent + diagnostics probes).

The rank-0 member of a rendezvous world publishes the jax.distributed
coordinator address through the master KV store; everyone else blocks on the
key. This replaces the reference's c10d TCPStore bootstrap
(elastic_agent/torch/master_kv_store.py).
"""

from __future__ import annotations

import socket

from dlrover_tpu.common.comm import local_ip


def free_port() -> int:
    sock = socket.socket()
    sock.bind(("", 0))
    port = sock.getsockname()[1]
    sock.close()
    return port


def publish_or_wait_coordinator(client, key: str, process_id: int,
                                timeout_s: float) -> str:
    """Rank 0 publishes `ip:port` under `key`; others wait for it."""
    if process_id == 0:
        coord = f"{local_ip()}:{free_port()}"
        client.kv_set(key, coord.encode())
        return coord
    return client.kv_wait(key, timeout_s=timeout_s).decode()
