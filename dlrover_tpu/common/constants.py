"""Framework-wide constants.

Capability parity with the reference's constant vocabulary
(dlrover/python/common/constants.py) — node types, statuses, the env-var
contract between master/agent/worker, rendezvous names, message levels —
re-spelled for a TPU/JAX deployment (hosts own TPU chips; worker processes are
JAX processes on TPU hosts).
"""


class PlatformType:
    LOCAL = "local"          # single-machine dev: master + agents as processes
    KUBERNETES = "k8s"       # GKE / k8s: pods per TPU host
    RAY = "ray"


class DistributionStrategy:
    ALLREDUCE = "allreduce"   # SPMD data/model parallel over a mesh
    PS = "ps"                 # parameter-server-style (elastic embeddings)
    LOCAL = "local"


class OptimizeMode:
    MANUAL = "manual"
    SINGLE_JOB = "single-job"
    CLUSTER = "cluster"       # ask the brain service for resource plans


class NodeType:
    MASTER = "master"
    WORKER = "worker"        # a TPU host running one JAX process
    CHIEF = "chief"          # worker rank 0 (does checkpoint writes, logging)
    EVALUATOR = "evaluator"
    PS = "ps"                # parameter-server-style state holder (embeddings)


class NodeStatus:
    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    UNKNOWN = "unknown"
    BREAKDOWN = "breakdown"  # machine-level fault (host unreachable)

    @classmethod
    def terminal(cls):
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED}


class NodeEventType:
    ADDED = "added"
    MODIFIED = "modified"
    DELETED = "deleted"


class NodeExitReason:
    SUCCEEDED = "succeeded"
    KILLED = "killed"            # deleted/force-killed by the platform
    # clean graceful drain (advance preemption notice honored: emergency
    # checkpoint completed, worker exited WorkerExit.DRAIN) — a planned
    # departure, not a failure: no relaunch-budget charge
    DRAINED = "drained"
    # self-aborted by the step-hang watchdog (stacks in the flight dump)
    HANG = "hang"
    OOM = "oom"                  # host or HBM out-of-memory
    FATAL_ERROR = "fatal_error"  # un-relaunchable user error
    HARDWARE_ERROR = "hardware_error"  # TPU chip / ICI fault
    UNKNOWN_ERROR = "unknown_error"
    RELAUNCHED = "relaunched"


class WorkerExit:
    """Worker exit-code vocabulary shared by the trainer (producer), the
    agent (classifier) and the k8s watcher (pod exit parsing)."""

    SUCCESS = 0
    # graceful drain after a preemption notice: the loop consumed the
    # drain request, ran the deadline-bounded emergency checkpoint and
    # exited clean. Chosen outside the shell (126/127) and signal
    # (128+n) ranges.
    DRAIN = 76
    # SIGABRT: the step-hang watchdog self-aborts so the agent restarts
    # the worker; Popen reports -6, k8s containers 128+6
    _SIGABRT_POPEN = -6
    _SIGABRT_SHELL = 134
    # platform SIGKILL/SIGTERM (eviction, force delete)
    _KILL_CODES = (-9, -15, 137, 143)

    @classmethod
    def classify(cls, code: int, hang_enabled: bool = True) -> str:
        """Exit code → NodeExitReason.* (the agent/diagnosis layer must
        tell drain from hang from crash from platform kill).

        ``hang_enabled``: with the step-hang watchdog off
        (``Context.hang_watchdog_s == 0``) a SIGABRT cannot be the
        watchdog — it is an ordinary crash (glibc abort, C++ terminate)
        and must charge the relaunch budget like one.
        """
        if code == cls.SUCCESS:
            return NodeExitReason.SUCCEEDED
        if code == cls.DRAIN:
            return NodeExitReason.DRAINED
        if code in (cls._SIGABRT_POPEN, cls._SIGABRT_SHELL):
            return (NodeExitReason.HANG if hang_enabled
                    else NodeExitReason.UNKNOWN_ERROR)
        if code in cls._KILL_CODES:
            return NodeExitReason.KILLED
        return NodeExitReason.UNKNOWN_ERROR

    @classmethod
    def to_exit_status(cls, code: int) -> int:
        """Popen's negative signal codes → the POSIX 128+N exit status
        a container reports. An agent re-exiting its worker's code must
        normalize, or -6 truncates to 250 at the process boundary and
        the pod-side classification can never see the hang/kill."""
        return 128 - code if code < 0 else code


class JobStage:
    CREATED = "created"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    STOPPING = "stopping"


class RendezvousName:
    TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class NodeEnv:
    """Env-var contract (reference: constants.py NodeEnv /
    NodeEnv.DLROVER_MASTER_ADDR)."""

    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    # File a (re)started master atomically writes its advertised address
    # into; agents in master-lost mode re-resolve from it (the address of
    # a restarted master usually differs — new pod IP / new free port).
    MASTER_BOOTSTRAP = "DLROVER_TPU_MASTER_BOOTSTRAP_FILE"
    # Coordination-tier address (master/coord_service.py): hot KV
    # traffic (dcn/ gradient exchange, coord/ barriers) dials this
    # instead of the control tier. Set by the agent for its worker from
    # the join result; "" / unset = single-tier master.
    COORD_ADDR = "DLROVER_TPU_COORD_ADDR"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_TYPE = "DLROVER_TPU_NODE_TYPE"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    # Per-worker (set by the agent for the spawned training process):
    WORLD_SIZE = "DLROVER_TPU_WORLD_SIZE"          # number of JAX processes
    PROCESS_ID = "DLROVER_TPU_PROCESS_ID"          # jax process index
    COORDINATOR_ADDR = "DLROVER_TPU_COORDINATOR"   # jax.distributed coordinator
    RDZV_ROUND = "DLROVER_TPU_RDZV_ROUND"
    PARAL_CONFIG_PATH = "DLROVER_TPU_PARAL_CONFIG" # tuned-config hot-reload file
    DEVICES_PER_NODE = "DLROVER_TPU_DEVICES_PER_NODE"
    # worker → agent handoff files (monitors tail these)
    METRICS_FILE = "DLROVER_TPU_METRICS_FILE"      # step-progress JSON lines
    CHIP_STATS_FILE = "DLROVER_TPU_CHIP_STATS"     # per-chip HBM usage JSON
    # per-step phase timeline ring the worker exports (obs/timeline.py)
    TIMELINE_FILE = "DLROVER_TPU_TIMELINE_FILE"
    # agent → worker handoff: on-demand profiler capture requests
    # (obs/profiler.py; the agent writes it when executing a master
    # `profile:{rank}` diagnosis action)
    PROFILE_REQUEST_FILE = "DLROVER_TPU_PROFILE_REQUEST"
    # agent → worker handoff: drain/checkpoint requests the step loop
    # polls (agent/preemption.py write_drain_request; the agent writes
    # it on a preemption notice — save+exit — or when executing a
    # master `checkpoint:{rank}` action — save+continue)
    DRAIN_REQUEST_FILE = "DLROVER_TPU_DRAIN_REQUEST"
    # host-RAM peer-state cache (checkpoint/peer_restore.py): the worker
    # stages its live state here at checkpoint boundaries; the agent's
    # donor server serves it to replacement ranks
    PEER_CACHE_DIR = "DLROVER_TPU_PEER_CACHE_DIR"
    # restore plan the agent received in its join result (JSON file);
    # workers with a master client re-fetch a fresh plan via RPC instead
    RESTORE_PLAN_FILE = "DLROVER_TPU_RESTORE_PLAN"
    # parallelism plan for the new world (parallel/planner.py), written
    # by the agent from its join result; workers with a master client
    # re-fetch fresh via ShardPlanRequest at loop build
    SHARD_PLAN_FILE = "DLROVER_TPU_SHARD_PLAN"
    # chaos `resize:+k@step` handoff: the injector atomically writes
    # the scale-up request here; the LAUNCHER (bench/test harness,
    # operator) consumes it and starts k more agents — adding ranks
    # needs a process spawner, which lives outside the worker
    RESIZE_REQUEST_FILE = "DLROVER_TPU_RESIZE_REQUEST"
    # total ICI slices of the job (slice-unit chaos resize targets the
    # k highest slice ids; unset = slice-unit resize faults disabled)
    NUM_SLICES = "DLROVER_TPU_NUM_SLICES"
    # platform/chaos → agent: a preemption-notice file the agent's
    # PreemptionWatcher polls ({"deadline": ts} or {"grace_s": n})
    PREEMPTION_NOTICE_FILE = "DLROVER_TPU_PREEMPTION_NOTICE"
    # k8s-style static notice: a unix timestamp set at pod creation
    # ("this VM goes away at T" — maintenance windows, spot reclaim)
    PREEMPTION_AT = "DLROVER_TPU_PREEMPTION_AT"
    # ICI slice this host belongs to (multi-slice hierarchical DP):
    # the slice is the failure domain — rendezvous worlds, drains and
    # restore-plan donor preference are all scoped by it. -1/unset =
    # single-slice job (every slice-scoped path disabled).
    SLICE_ID = "DLROVER_TPU_SLICE_ID"


class TrainingMsgLevel:
    PROCESS_ERROR = "process_error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    WARNING = "warning"
    INFO = "info"


class TaskType:
    """Dynamic-sharding task types (reference: master/shard)."""

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"
    NONE = "none"


class NetworkCheckResult:
    NORMAL = "normal"
    FAULT = "fault"
    STRAGGLER = "straggler"


class MeshAxis:
    """Canonical named mesh axes (replaces the reference's named process groups,
    atorch/distributed/distributed.py:323 create_parallel_group)."""

    # cross-slice data parallelism over the slow DCN fabric (multi-slice
    # hierarchical DP): the OUTERMOST axis — gradient sync runs in-slice
    # over ICI first, then (all-)reduces over this axis
    DCN = "dcn"
    DATA = "data"
    FSDP = "fsdp"
    TENSOR = "tensor"
    SEQUENCE = "sequence"
    EXPERT = "expert"
    PIPE = "pipe"

    ALL = ("dcn", "data", "fsdp", "tensor", "sequence", "expert", "pipe")


class DefaultValues:
    MASTER_PORT = 0                 # 0 → pick a free port
    METRICS_PORT = 0                # /metrics exposition; 0 → free port,
    #                                 -1 → disabled
    RDZV_TIMEOUT_S = 600.0
    RDZV_WAIT_NEW_NODE_S = 30.0     # grace window for extra nodes past min
    TASK_TIMEOUT_S = 1800.0
    HEARTBEAT_INTERVAL_S = 15.0
    HANG_SECONDS = 1800.0
    # an agent silent this long is declared dead: its rendezvous world is
    # invalidated so survivors re-form (the scale-DOWN path). Liveness is
    # touched by join/get_comm_world/num_nodes_waiting RPCs — any healthy
    # agent beats far faster than this.
    DEAD_NODE_TIMEOUT_S = 90.0
    MAX_RELAUNCH = 3
    GRPC_MAX_MESSAGE_MB = 64
    # client-side RPC budget: jittered exponential backoff between
    # attempts, capped (agent/master_client.py retry_rpc)
    RPC_TIMEOUT_S = 30.0
    RPC_RETRIES = 10
    RPC_BACKOFF_S = 0.5
    RPC_BACKOFF_MAX_S = 15.0
    # master-loss handling (agent/elastic_agent.py): how long an agent
    # keeps its workers alive while reconnecting to a restarted master
    MASTER_RECONNECT_TIMEOUT_S = 1800.0
    # crash-consistent master state (master/state_backend.py)
    MASTER_SNAPSHOT_RETAIN = 5
    # 0 = write-through (a snapshot per control-plane mutation: strict
    # no-loss/no-double-assign recovery). > 0 coalesces snapshots to at
    # most one per interval — bounds write amplification on
    # dispatch-heavy phases at the cost of up to that much durability
    # lag on a crash (docs/fault_tolerance.md)
    MASTER_SNAPSHOT_MIN_INTERVAL_S = 0.0
    # -- sharded control plane (master/rendezvous_shards.py) ------------
    # per-slice rendezvous shards behind a router: a wedged slice's
    # joins cannot delay another slice's cut, and a shard restarts
    # alone. False reverts JobMaster to the single-lock manager (the
    # bench baseline).
    RDZV_SHARDED = True
    # the KV/coordination tier's own port (master/coord_service.py):
    # 0 = any free port, -1 = serve coordination on the main port only
    COORD_PORT = 0
    # bounded telemetry ingest: reports queued past this are dropped
    # oldest-first (dlrover_tpu_telemetry_dropped_total)
    TELEMETRY_QUEUE_SIZE = 256
    # kv episode hygiene: generations of a namespaced hot-key group
    # retained (current + N-1 for in-flight readers of the superseded
    # episode); older generations are garbage-collected on write
    KV_GC_KEEP_GENERATIONS = 2
    # -- hot-standby master (master/standby.py) -------------------------
    # cadence of the standby's primary health probe, and how many
    # consecutive failed probes trigger promotion
    STANDBY_HEALTH_INTERVAL_S = 2.0
    STANDBY_PROMOTE_FAILURES = 3
    KV_WAIT_TIMEOUT_S = 300.0
    MONITOR_INTERVAL_S = 5.0
    REPORT_RESOURCE_INTERVAL_S = 15.0
    SPEED_SAMPLE_WINDOW = 20
    STRAGGLER_MEDIAN_RATIO = 2.0    # t > ratio × median ⇒ straggler
    SECONDS_PER_SCALE_CHECK = 60.0
    # training diagnosis engine (master/diagnosis/): the rule-based
    # inference chain over per-worker step reports + resource stats
    DIAGNOSIS_ENABLED = True
    DIAGNOSIS_INTERVAL_S = 30.0
    # per-worker step-time window (samples) straggler scoring runs over
    DIAGNOSIS_WORKER_WINDOW = 20
    # a worker needs this many samples before rules will judge it (a
    # fresh joiner's first post-compile reports are not evidence)
    DIAGNOSIS_MIN_WORKER_SAMPLES = 3
    # hysteresis: consecutive over-threshold evaluations before a
    # straggler is flagged, and consecutive clean ones before it clears
    STRAGGLER_TRIGGER_WINDOWS = 2
    STRAGGLER_CLEAR_WINDOWS = 2
    # data-pipeline-bound attribution: windowed data-wait fraction above
    # this means the step loop starves on input, not on compute
    DIAGNOSIS_DATA_WAIT_FRACTION = 0.5
    # HBM-pressure warning threshold (per-chip used/total %)
    DIAGNOSIS_HBM_PRESSURE_PCT = 92.0
    # throughput collapse: windowed steps/s under ratio × the observed
    # high-water mark (with training in steady state) raises a report
    DIAGNOSIS_COLLAPSE_RATIO = 0.5
    # action grammar: observe / profile:{rank} / restart:{rank} / alert.
    # False = diagnose-only (reports + metrics, no actions dispatched)
    DIAGNOSIS_ACTIONS_ENABLED = True
    # steps an on-demand profiler capture traces on the target worker
    DIAGNOSIS_PROFILE_STEPS = 5
    # per-rank cooldown between dispatched actions (a straggler that
    # stays slow must not get a profile request every interval)
    DIAGNOSIS_ACTION_COOLDOWN_S = 300.0
    # goodput alerting (obs/goodput.py + GoodputRule): alert when the
    # productive fraction over the trailing window drops below the
    # threshold, naming the dominant badput bucket. 0 = disabled (the
    # default: an acceptable goodput floor is job-specific).
    GOODPUT_ALERT_THRESHOLD = 0.0
    GOODPUT_WINDOW_S = 600.0
    # the window must be at least this covered (elapsed rank-seconds /
    # window) before the rule judges it — a freshly-started world's
    # first half-window is not evidence of lost goodput
    GOODPUT_MIN_COVERAGE = 0.5
    # -- fleet time-series plane (obs/tsdb.py) --------------------------
    # cadence the master's collector samples the allowlisted registry
    # gauges + goodput snapshot into the history store; 0 = no sampler
    # thread (direct step-report ingest still runs)
    TSDB_SAMPLE_INTERVAL_S = 5.0
    # cadence the downsampled tiers persist to the state-dir sidecar
    # (bounded history loss on a hard master kill); 0 = flush only on
    # graceful stop
    TSDB_FLUSH_INTERVAL_S = 30.0
    # -- planner calibration (parallel/calibration.py) ------------------
    # measurements a plan signature needs before it is calibration
    # evidence (each sample is already a windowed worker mean)
    CALIBRATION_MIN_SAMPLES = 3
    # PlanRegressionRule: alert when measured step time exceeds the
    # planner's prediction by this ratio for PLAN_REGRESSION_WINDOWS
    # consecutive diagnosis rounds (hysteresis like StragglerRule);
    # clears after PLAN_REGRESSION_CLEAR_WINDOWS under it. ratio 0 =
    # rule disabled.
    PLAN_REGRESSION_RATIO = 1.5
    PLAN_REGRESSION_WINDOWS = 3
    PLAN_REGRESSION_CLEAR_WINDOWS = 2
    # -- preemption-aware graceful drain (agent/preemption.py) ----------
    # grace window assumed when a notice carries no deadline (a bare
    # SIGTERM): k8s default terminationGracePeriodSeconds
    PREEMPT_DEFAULT_GRACE_S = 30.0
    # cadence of the agent's notice-source poll (file/env sources)
    PREEMPT_NOTICE_POLL_S = 1.0
    # how far ahead of a static env deadline ($DLROVER_TPU_PREEMPTION_AT)
    # the drain fires; 0 = use preempt_default_grace_s. Jobs whose full
    # save takes longer than the bare-SIGTERM grace must widen this or
    # the emergency save is skipped despite hours of advance notice.
    PREEMPT_ENV_HORIZON_S = 0.0
    # emergency checkpoint: skip-and-log when the remaining window is
    # below this floor (a save that cannot commit only produces a torn
    # step the restore fallback then has to walk past)
    EMERGENCY_CKPT_MIN_WINDOW_S = 2.0
    # -- peer-to-peer elastic restore (checkpoint/peer_restore.py) ------
    # serve a replacement rank's shards from surviving hosts' staged
    # state instead of Orbax storage (restore time independent of model
    # size); False reverts every restore to the storage path
    PEER_RESTORE_ENABLED = True
    # wall-clock budget for the peer shard transfer: past it the restore
    # aborts shard-wise to the Orbax fallback instead of hanging
    PEER_RESTORE_TIMEOUT_S = 120.0
    # donor server port (0 = ephemeral; the advertised addr rides the
    # PeerStoreReport RPC either way)
    PEER_DONOR_PORT = 0
    # -- online parallelism re-planning (parallel/planner.py) -----------
    # apply the master's shard plan when building the worker's mesh
    # (mesh spec + batch/accumulation override); False pins the
    # configured mesh — resizes then only re-form the same DP shape
    REPLAN_ENABLED = True
    # -- step-hang watchdog (trainer/watchdog.py) -----------------------
    # no step progress for this long → dump all-thread stacks + the
    # flight record and self-abort so the agent restarts the worker.
    # 0 = disabled (the default: legitimate step times vary too much to
    # pick a universal bound; jobs opt in via DLROVER_TPU_HANG_WATCHDOG_S)
    HANG_WATCHDOG_S = 0.0
    # -- multi-slice hierarchical DP (parallel/dcn_sync.py) -------------
    # degraded-mode budget: surviving slices keep stepping with the
    # gradient mean renormalized over PRESENT slices for this many
    # consecutive steps while a slice is absent (draining/re-forming);
    # past it they hard-stall with a CRITICAL alert instead of silently
    # training on a shrunken mean
    SLICE_ABSENT_MAX_STEPS = 100
    # per-step deadline for collecting a formed peer slice's gradient
    # contribution over DCN; a formed slice silent past it is treated
    # absent for THIS step (degraded accounting, loud warning)
    DCN_SYNC_TIMEOUT_S = 60.0
    # cadence of the collector's poll against the master KV store
    DCN_SYNC_POLL_S = 0.05
    # int8/int4 groupwise quantization of the host-level cross-slice
    # gradient payloads (checkpoint/quantized.py codec — the same
    # scheme quant_collectives puts on the wire in-program); 0 = exact
    # float32 bytes
    DCN_SYNC_QUANT_BITS = 0
    # -- per-step critical-path tracing (obs/steptrace.py) --------------
    # worker-side: emit one compact trace record per step, batched over
    # the TelemetryReport channel; False turns the recorder off (the
    # StepTimeline windowed export keeps running either way)
    STEPTRACE_ENABLED = True
    # bounded drop-oldest record ring between flushes (a wedged master
    # must not grow worker memory)
    STEPTRACE_RING = 512
    # NTP-style clock-offset refresh cadence against the master (the
    # join-time probe always runs; refreshes ride the report cadence)
    STEPTRACE_PROBE_INTERVAL_S = 30.0
    # master-side: assembled (gen, step) groups the StepTraceAssembler
    # retains for queries / the flight embed
    STEPTRACE_RING_STEPS = 512
    # CriticalPathRule: flag a rank after it gated at least this
    # fraction of the window's solved steps for
    # STRAGGLER_TRIGGER_WINDOWS consecutive evaluations (clears after
    # STRAGGLER_CLEAR_WINDOWS under — the same hysteresis knobs as
    # StragglerRule); 0 disables the rule
    CRITICAL_PATH_GATING_FRACTION = 0.5
    # -- flight recorder rings (obs/flight_recorder.py) -----------------
    # per-process bounded event ring and span-id dedup ring (historically
    # one hard-coded 4096)
    FLIGHT_RING_EVENTS = 4096
    FLIGHT_RING_SPANS = 4096
    # -- goodput-optimal fleet controller (brain/fleet_controller.py) ---
    # master-side control loop that claims offered preemptible slices,
    # sheds a gating slice, or holds — every actuation through the
    # existing drain/rejoin machinery. Off by default: the controller
    # changes fleet membership on its own authority; jobs opt in.
    FLEET_CONTROLLER_ENABLED = False
    # evaluation cadence of the control loop
    AUTOSCALE_INTERVAL_S = 30.0
    # after any actuation, no new decision for this long (lets the
    # rollback watchdog's observation window conclude first)
    AUTOSCALE_COOLDOWN_S = 120.0
    # hysteresis: consecutive evaluations agreeing on the same decision
    # before it actuates (one noisy window must not resize the fleet)
    AUTOSCALE_HYSTERESIS_WINDOWS = 2
    # hard ceiling on actuations per hour, claims and sheds combined
    # (rollbacks are exempt — undoing damage must never be rate-limited)
    AUTOSCALE_MAX_DECISIONS_PER_HOUR = 6
    # rollback watchdog: windowed goodput fraction dropping by more than
    # this (absolute) versus the pre-actuation window reverts the
    # decision and quarantines its class
    AUTOSCALE_ROLLBACK_DROP_FRACTION = 0.2
    # how long after an actuation the watchdog compares windows
    AUTOSCALE_ROLLBACK_WINDOW_S = 120.0
    # quarantine base for a rolled-back decision class; doubles per
    # consecutive rollback of the same class, capped at 8x
    AUTOSCALE_QUARANTINE_BACKOFF_S = 600.0
    # claim economics: predicted marginal goodput (rank-seconds over the
    # offer's expected lifetime) must exceed the join+re-plan cost
    # estimate by this ratio before a claim fires
    AUTOSCALE_CLAIM_MARGIN = 1.2
    # shed trigger: steptrace must name the slice gating AND the fleet's
    # cross-slice wait fraction must exceed this
    AUTOSCALE_SHED_WAIT_FRACTION = 0.3
    # -- speed-aware dynamic sharding (master/shard/) -------------------
    # weight get_task dispatch by observed per-rank speed so faster
    # workers pull more shards; False = byte-identical legacy dispatch
    DISPATCH_SPEED_WEIGHTED = False
    # the slowest rank is still served at least one shard per this many
    # fleet dispatches (throttle, never starvation)
    DISPATCH_WEIGHT_FLOOR = 0.25
    # -- data-pipeline auto-tune (data/prefetch.py) ---------------------
    # grow device-prefetch depth / shm-ring capacity while the
    # timeline's data_wait fraction stays above the trigger; shrink back
    # when the pipeline stops starving. Advisory values consumed at
    # (re)build boundaries — never mid-step.
    PREFETCH_AUTOTUNE = True
    PREFETCH_DEPTH_MIN = 1
    PREFETCH_DEPTH_MAX = 8
    DATA_WAIT_TUNE_FRACTION = 0.2
    # -- per-rank relaunch backoff + quarantine (agent) -----------------
    # exponential delay between worker relaunches: base * 2^(k-1) for the
    # k-th recent failure, capped — a flapping worker must not hot-loop
    RELAUNCH_BACKOFF_BASE_S = 1.0
    RELAUNCH_BACKOFF_MAX_S = 60.0
    # quarantine the rank (stop relaunching; agent exits with the worker
    # code) after this many failures inside the window; 0 disables
    QUARANTINE_FAILURES = 5
    QUARANTINE_WINDOW_S = 600.0


# The hot-tier KV contract, shared by the master (snapshot exemption +
# mutation log + generation GC, master/kv_store.py) and the client
# (coordination-tier routing, agent/master_client.py): keys under these
# prefixes are on the gradient path. ONE constant — a prefix added to
# only one side would silently route hot traffic to the control tier or
# skip snapshotting a cold key.
HOT_KV_PREFIXES = ("dcn/", "coord/")

# The durable subset of the hot prefixes: coord/ barrier mutations ride
# the mutation log (a promoted master must answer the coordinator
# addresses agents kv_wait on), dcn/ payloads are per-step ephemeral by
# protocol and never logged. Lives HERE beside HOT_KV_PREFIXES — the
# same single-sourcing contract (graftlint GL403): a prefix split
# between kv_store and a future standby replay path would silently
# diverge durability.
LOGGED_KV_PREFIXES = ("coord/",)
