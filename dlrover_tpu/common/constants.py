"""Framework-wide constants.

Capability parity with the reference's constant vocabulary
(dlrover/python/common/constants.py) — node types, statuses, the env-var
contract between master/agent/worker, rendezvous names, message levels —
re-spelled for a TPU/JAX deployment (hosts own TPU chips; worker processes are
JAX processes on TPU hosts).
"""


class PlatformType:
    LOCAL = "local"          # single-machine dev: master + agents as processes
    KUBERNETES = "k8s"       # GKE / k8s: pods per TPU host
    RAY = "ray"


class DistributionStrategy:
    ALLREDUCE = "allreduce"   # SPMD data/model parallel over a mesh
    PS = "ps"                 # parameter-server-style (elastic embeddings)
    LOCAL = "local"


class OptimizeMode:
    MANUAL = "manual"
    SINGLE_JOB = "single-job"
    CLUSTER = "cluster"       # ask the brain service for resource plans


class NodeType:
    MASTER = "master"
    WORKER = "worker"        # a TPU host running one JAX process
    CHIEF = "chief"          # worker rank 0 (does checkpoint writes, logging)
    EVALUATOR = "evaluator"
    PS = "ps"                # parameter-server-style state holder (embeddings)


class NodeStatus:
    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    UNKNOWN = "unknown"
    BREAKDOWN = "breakdown"  # machine-level fault (host unreachable)

    @classmethod
    def terminal(cls):
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED}


class NodeEventType:
    ADDED = "added"
    MODIFIED = "modified"
    DELETED = "deleted"


class NodeExitReason:
    SUCCEEDED = "succeeded"
    KILLED = "killed"            # deleted/preempted by the platform
    OOM = "oom"                  # host or HBM out-of-memory
    FATAL_ERROR = "fatal_error"  # un-relaunchable user error
    HARDWARE_ERROR = "hardware_error"  # TPU chip / ICI fault
    UNKNOWN_ERROR = "unknown_error"
    RELAUNCHED = "relaunched"


class JobStage:
    CREATED = "created"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    STOPPING = "stopping"


class RendezvousName:
    TRAINING = "elastic-training"
    NETWORK_CHECK = "network-check"


class NodeEnv:
    """Env-var contract (reference: constants.py NodeEnv /
    NodeEnv.DLROVER_MASTER_ADDR)."""

    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    # File a (re)started master atomically writes its advertised address
    # into; agents in master-lost mode re-resolve from it (the address of
    # a restarted master usually differs — new pod IP / new free port).
    MASTER_BOOTSTRAP = "DLROVER_TPU_MASTER_BOOTSTRAP_FILE"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_TYPE = "DLROVER_TPU_NODE_TYPE"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    # Per-worker (set by the agent for the spawned training process):
    WORLD_SIZE = "DLROVER_TPU_WORLD_SIZE"          # number of JAX processes
    PROCESS_ID = "DLROVER_TPU_PROCESS_ID"          # jax process index
    COORDINATOR_ADDR = "DLROVER_TPU_COORDINATOR"   # jax.distributed coordinator
    RDZV_ROUND = "DLROVER_TPU_RDZV_ROUND"
    PARAL_CONFIG_PATH = "DLROVER_TPU_PARAL_CONFIG" # tuned-config hot-reload file
    DEVICES_PER_NODE = "DLROVER_TPU_DEVICES_PER_NODE"
    # worker → agent handoff files (monitors tail these)
    METRICS_FILE = "DLROVER_TPU_METRICS_FILE"      # step-progress JSON lines
    CHIP_STATS_FILE = "DLROVER_TPU_CHIP_STATS"     # per-chip HBM usage JSON
    # per-step phase timeline ring the worker exports (obs/timeline.py)
    TIMELINE_FILE = "DLROVER_TPU_TIMELINE_FILE"
    # agent → worker handoff: on-demand profiler capture requests
    # (obs/profiler.py; the agent writes it when executing a master
    # `profile:{rank}` diagnosis action)
    PROFILE_REQUEST_FILE = "DLROVER_TPU_PROFILE_REQUEST"


class TrainingMsgLevel:
    PROCESS_ERROR = "process_error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    WARNING = "warning"
    INFO = "info"


class TaskType:
    """Dynamic-sharding task types (reference: master/shard)."""

    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"
    WAIT = "wait"
    NONE = "none"


class NetworkCheckResult:
    NORMAL = "normal"
    FAULT = "fault"
    STRAGGLER = "straggler"


class MeshAxis:
    """Canonical named mesh axes (replaces the reference's named process groups,
    atorch/distributed/distributed.py:323 create_parallel_group)."""

    DATA = "data"
    FSDP = "fsdp"
    TENSOR = "tensor"
    SEQUENCE = "sequence"
    EXPERT = "expert"
    PIPE = "pipe"

    ALL = ("data", "fsdp", "tensor", "sequence", "expert", "pipe")


class DefaultValues:
    MASTER_PORT = 0                 # 0 → pick a free port
    METRICS_PORT = 0                # /metrics exposition; 0 → free port,
    #                                 -1 → disabled
    RDZV_TIMEOUT_S = 600.0
    RDZV_WAIT_NEW_NODE_S = 30.0     # grace window for extra nodes past min
    TASK_TIMEOUT_S = 1800.0
    HEARTBEAT_INTERVAL_S = 15.0
    HANG_SECONDS = 1800.0
    # an agent silent this long is declared dead: its rendezvous world is
    # invalidated so survivors re-form (the scale-DOWN path). Liveness is
    # touched by join/get_comm_world/num_nodes_waiting RPCs — any healthy
    # agent beats far faster than this.
    DEAD_NODE_TIMEOUT_S = 90.0
    MAX_RELAUNCH = 3
    GRPC_MAX_MESSAGE_MB = 64
    # client-side RPC budget: jittered exponential backoff between
    # attempts, capped (agent/master_client.py retry_rpc)
    RPC_TIMEOUT_S = 30.0
    RPC_RETRIES = 10
    RPC_BACKOFF_S = 0.5
    RPC_BACKOFF_MAX_S = 15.0
    # master-loss handling (agent/elastic_agent.py): how long an agent
    # keeps its workers alive while reconnecting to a restarted master
    MASTER_RECONNECT_TIMEOUT_S = 1800.0
    # crash-consistent master state (master/state_backend.py)
    MASTER_SNAPSHOT_RETAIN = 5
    # 0 = write-through (a snapshot per control-plane mutation: strict
    # no-loss/no-double-assign recovery). > 0 coalesces snapshots to at
    # most one per interval — bounds write amplification on
    # dispatch-heavy phases at the cost of up to that much durability
    # lag on a crash (docs/fault_tolerance.md)
    MASTER_SNAPSHOT_MIN_INTERVAL_S = 0.0
    KV_WAIT_TIMEOUT_S = 300.0
    MONITOR_INTERVAL_S = 5.0
    REPORT_RESOURCE_INTERVAL_S = 15.0
    SPEED_SAMPLE_WINDOW = 20
    STRAGGLER_MEDIAN_RATIO = 2.0    # t > ratio × median ⇒ straggler
    SECONDS_PER_SCALE_CHECK = 60.0
    # training diagnosis engine (master/diagnosis/): the rule-based
    # inference chain over per-worker step reports + resource stats
    DIAGNOSIS_ENABLED = True
    DIAGNOSIS_INTERVAL_S = 30.0
    # per-worker step-time window (samples) straggler scoring runs over
    DIAGNOSIS_WORKER_WINDOW = 20
    # a worker needs this many samples before rules will judge it (a
    # fresh joiner's first post-compile reports are not evidence)
    DIAGNOSIS_MIN_WORKER_SAMPLES = 3
    # hysteresis: consecutive over-threshold evaluations before a
    # straggler is flagged, and consecutive clean ones before it clears
    STRAGGLER_TRIGGER_WINDOWS = 2
    STRAGGLER_CLEAR_WINDOWS = 2
    # data-pipeline-bound attribution: windowed data-wait fraction above
    # this means the step loop starves on input, not on compute
    DIAGNOSIS_DATA_WAIT_FRACTION = 0.5
    # HBM-pressure warning threshold (per-chip used/total %)
    DIAGNOSIS_HBM_PRESSURE_PCT = 92.0
    # throughput collapse: windowed steps/s under ratio × the observed
    # high-water mark (with training in steady state) raises a report
    DIAGNOSIS_COLLAPSE_RATIO = 0.5
    # action grammar: observe / profile:{rank} / restart:{rank} / alert.
    # False = diagnose-only (reports + metrics, no actions dispatched)
    DIAGNOSIS_ACTIONS_ENABLED = True
    # steps an on-demand profiler capture traces on the target worker
    DIAGNOSIS_PROFILE_STEPS = 5
    # per-rank cooldown between dispatched actions (a straggler that
    # stays slow must not get a profile request every interval)
    DIAGNOSIS_ACTION_COOLDOWN_S = 300.0
