"""Pallas TPU flash attention with custom VJP.

TPU-native equivalent of the reference's flash-attention integration
(atorch/atorch/modules/transformer/layers.py:740-1279 binds CUDA flash-attn
into BERT/LLaMA/GLM blocks) — re-designed as a blockwise online-softmax
kernel for the MXU instead of a CUDA binding:

- forward: grid (batch, heads, q_blocks, kv_blocks); the kv axis is the
  innermost (sequential on TPU), accumulating (acc, row-max m, row-sum l) in
  VMEM scratch; causal blocks above the diagonal are skipped cheaply.
- block sizes default to 1024x1024 (v5e-tuned: 92 TF/s fwd vs 11 at
  128x128; capped by seq len so small shapes still work).
- backward: two kernels — dq accumulates over kv blocks; dk/dv accumulate
  over q blocks — using the saved logsumexp and delta = rowsum(dO*O).
- GQA: kv heads are indexed as h // (num_q_heads // num_kv_heads) directly
  in the BlockSpec index maps; no materialized head broadcast.

MXU matmuls run in the input dtype (bf16 at full rate) with fp32
accumulation via `preferred_element_type` — FlashAttention-2 numerics; the
softmax statistics are always fp32. On non-TPU backends the kernels run in
Pallas interpret mode, so tests validate the same code path on the virtual
CPU platform.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dlrover_tpu.common.jax_compat import (
    get_vma,
    shape_dtype_struct,
    shard_map,
    tpu_compiler_params,
)

NEG_INF = -1e30

# exp2-domain softmax: fold log2(e) into the score scale so every
# transcendental in the kernels is a bare exp2 (TPU lowers exp via exp2
# anyway; doing it explicitly saves the per-element argument multiply).
# The SAVED logsumexp stays in natural-log units — ring attention
# (parallel/ring_attention.py) merges lse across ring steps with
# natural exp/log.
LOG2E = 1.4426950408889634
LN2 = 0.6931471805599453

# v5e-tuned default block sizes (92 TF/s fwd vs 11 at 128×128); capped by
# the actual sequence length via fit_block. Shared with the ring-flash
# path (parallel/ring_attention.py).
DEFAULT_BLOCK_Q = 1024
DEFAULT_BLOCK_K = 1024

# Grid axes (batch, heads, outer-block) are independent; the innermost
# axis carries the VMEM accumulators and must stay sequential.
_DIM_SEMANTICS = tpu_compiler_params(
    dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"))


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _vma(*arrays) -> frozenset:
    """Union of the inputs' varying-manual-axes: under a check_vma
    shard_map (e.g. the pipeline's manual `pipe` axis) pallas_call
    outputs must declare how they vary."""
    u: frozenset = frozenset()
    for a in arrays:
        u = u | get_vma(a)
    return u


def _sds(shape, dtype, vma):
    return shape_dtype_struct(shape, dtype, vma=vma)


def _cdiv(a: int, b: int) -> int:
    return (a + b - 1) // b


def _causal_dispatch(compute, q_start, k_start,
                     block_q: int, block_k: int) -> None:
    """Run `compute(masked)` for a causal (q, k) block pair: skip blocks
    entirely above the diagonal, and pay the iota/select mask VPU work
    only on blocks that straddle it. Static per-block skip is impossible
    (q_start/k_start are dynamic over the grid), so dispatch with
    pl.when. Shared by the forward and both backward kernels so the
    boundary conditions cannot drift apart."""
    needed = k_start <= q_start + block_q - 1
    full = k_start + block_k - 1 <= q_start
    pl.when(jnp.logical_and(needed, full))(
        lambda: compute(False))
    pl.when(jnp.logical_and(needed, jnp.logical_not(full)))(
        lambda: compute(True))


def fit_block(n: int, block: int) -> int:
    """Largest divisor of n that is <= block.

    Pallas pads out-of-bounds block rows with undefined data on real TPU
    (interpret mode zero-pads, so CPU tests can't catch it); requiring the
    block to divide the dimension keeps every block fully in-bounds.
    Prefers multiples of 128 (lane width) when one divides n.
    """
    block = min(block, n)
    aligned = (block // 128) * 128
    while aligned >= 128:
        if n % aligned == 0:
            return aligned
        aligned -= 128
    for b in range(block, 0, -1):
        if n % b == 0:
            return b
    return n


# ===========================================================================
# Forward kernel
# ===========================================================================


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref,
                *, sm_scale: float, causal: bool,
                block_q: int, block_k: int, num_k_blocks: int):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    qi = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    def _compute(masked: bool):
        # Inputs stay in their native dtype (bf16) so the MXU runs at full
        # rate; accumulation is fp32 via preferred_element_type (the
        # FlashAttention-2 numerics). fp32 operands pass through unchanged.
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * (
            sm_scale * LOG2E)
        if masked:
            q_idx = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_idx = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        m_prev = m_ref[:]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp2(s - m_new)
        alpha = jnp.exp2(m_prev - m_new)
        l_ref[:] = l_ref[:] * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # Only blocks straddling the diagonal pay the iota/select VPU
        # work (at seq 2048 that's 2 of 3 computed blocks; at 8k only
        # 8 of 36).
        _causal_dispatch(_compute, q_start, k_start, block_q, block_k)
    else:
        _compute(False)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        l = l_ref[:]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[:] + jnp.log2(l_safe)) * LN2


def _flash_fwd(q, k, v, sm_scale: float, causal: bool,
               block_q: int, block_k: int):
    batch, num_heads, seq_q, head_dim = q.shape
    _, num_kv_heads, seq_k, _ = k.shape
    group = num_heads // num_kv_heads
    block_q = fit_block(seq_q, block_q)
    block_k = fit_block(seq_k, block_k)
    num_q_blocks = _cdiv(seq_q, block_q)
    num_k_blocks = _cdiv(seq_k, block_k)

    grid = (batch, num_heads, num_q_blocks, num_k_blocks)

    def q_map(b, h, qi, ki):
        return (b, h, qi, 0)

    def kv_map(b, h, qi, ki):
        if causal:
            # Blocks above the diagonal are skipped by the kernel; map
            # their kv index to the last needed block so consecutive
            # grid steps see the same index and Pallas elides the DMA.
            ki = jnp.minimum(ki, ((qi + 1) * block_q - 1) // block_k)
        return (b, h // group, ki, 0)

    def o_map(b, h, qi, ki):
        return (b, h, qi, 0)

    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=num_k_blocks,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim), q_map),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_map),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_map),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim), o_map),
            pl.BlockSpec((1, 1, block_q, 1),
                         lambda b, h, qi, ki: (b, h, qi, 0)),
        ],
        out_shape=[
            _sds(q.shape, q.dtype, _vma(q, k, v)),
            _sds((batch, num_heads, seq_q, 1), jnp.float32, _vma(q, k, v)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, head_dim), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=_use_interpret(),
    )(q, k, v)
    return out, lse


# ===========================================================================
# Backward kernels
# ===========================================================================


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_acc_ref,
                   *, sm_scale: float, causal: bool,
                   block_q: int, block_k: int, num_k_blocks: int):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    qi = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    def _compute(masked: bool):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0] * LOG2E    # nat -> exp2 domain (per row)
        delta = delta_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * (
            sm_scale * LOG2E)
        if masked:
            q_idx = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_idx = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        p = jnp.exp2(s - lse)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(k.dtype)
        dq_acc_ref[:] += jnp.dot(ds, k, preferred_element_type=jnp.float32)

    if causal:
        _causal_dispatch(_compute, q_start, k_start, block_q, block_k)
    else:
        _compute(False)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        dq_ref[0, 0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc_ref, dv_acc_ref,
                    *, sm_scale: float, causal: bool,
                    block_q: int, block_k: int, num_q_blocks: int):
    qi = pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    ki = pl.program_id(2)
    q_start = qi * block_q
    k_start = ki * block_k

    def _compute(masked: bool):
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0] * LOG2E    # nat -> exp2 domain (per row)
        delta = delta_ref[0, 0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32) * (
            sm_scale * LOG2E)
        if masked:
            q_idx = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_idx = k_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_idx >= k_idx, s, NEG_INF)
        p = jnp.exp2(s - lse)
        dv_acc_ref[:] += jnp.dot(p.astype(do.dtype).T, do,
                                 preferred_element_type=jnp.float32)
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * sm_scale).astype(q.dtype)
        dk_acc_ref[:] += jnp.dot(ds.T, q,
                                 preferred_element_type=jnp.float32)

    if causal:
        # For a kv block, only q blocks at or below the diagonal
        # contribute; blocks strictly below it need no mask.
        _causal_dispatch(_compute, q_start, k_start, block_q, block_k)
    else:
        _compute(False)

    @pl.when(qi == num_q_blocks - 1)
    def _finalize():
        dk_ref[0, 0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _flash_bwd(res, g, *, sm_scale: float, causal: bool,
               block_q: int, block_k: int, delta=None):
    """delta = rowsum(dO·O) may be passed precomputed — ring callers
    invoke this once per visiting KV block with step-invariant dO/O."""
    q, k, v, out, lse = res
    do = g
    batch, num_heads, seq_q, head_dim = q.shape
    _, num_kv_heads, seq_k, _ = k.shape
    group = num_heads // num_kv_heads
    block_q = fit_block(seq_q, block_q)
    block_k = fit_block(seq_k, block_k)
    num_q_blocks = _cdiv(seq_q, block_q)
    num_k_blocks = _cdiv(seq_k, block_k)

    if delta is None:
        delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                        axis=-1, keepdims=True)  # (b, h, seq_q, 1)

    def q_map(b, h, qi, ki):
        return (b, h, qi, 0)

    def kv_map(b, h, qi, ki):
        if causal:
            # dedupe the DMA of kv blocks above the diagonal (skipped by
            # the kernel): same trick as the forward's kv_map
            ki = jnp.minimum(ki, ((qi + 1) * block_q - 1) // block_k)
        return (b, h // group, ki, 0)

    def row_map(b, h, qi, ki):
        return (b, h, qi, 0)

    # ---- dq: iterate kv blocks innermost -----------------------------
    dq_kernel = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k_blocks=num_k_blocks,
    )
    dq = pl.pallas_call(
        dq_kernel,
        grid=(batch, num_heads, num_q_blocks, num_k_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim), q_map),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_map),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_map),
            pl.BlockSpec((1, 1, block_q, head_dim), q_map),
            pl.BlockSpec((1, 1, block_q, 1), row_map),
            pl.BlockSpec((1, 1, block_q, 1), row_map),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, head_dim), q_map),
        out_shape=_sds(q.shape, q.dtype, _vma(q, k, v, do)),
        scratch_shapes=[pltpu.VMEM((block_q, head_dim), jnp.float32)],
        compiler_params=_DIM_SEMANTICS,
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    # ---- dk/dv: per q-head contributions, iterate q blocks innermost --
    # Grid runs over *query* heads so GQA contributions are disjoint per
    # (kv-head, group member); sum over the group afterwards.
    def kv_out_map(b, h, ki, qi):
        return (b, h, ki, 0)

    if causal:
        # dedupe the DMA of q/do/lse/delta blocks strictly above the
        # diagonal (skipped by the kernel): clamp to the first
        # contributing q block for this kv block. The upper clamp keeps
        # the index in range when seq_k > seq_q (trailing kv blocks have
        # no contributing q block at all — the kernel skips them, but
        # the index map must still be in bounds: on real TPU an OOB
        # block DMAs undefined memory).
        def _qi_eff(ki, qi):
            return jnp.minimum(
                jnp.maximum(qi, (ki * block_k) // block_q),
                num_q_blocks - 1)
    else:
        def _qi_eff(ki, qi):
            return qi

    def q_map2(b, h, ki, qi):
        return (b, h, _qi_eff(ki, qi), 0)

    def kv_map2(b, h, ki, qi):
        return (b, h // group, ki, 0)

    def row_map2(b, h, ki, qi):
        return (b, h, _qi_eff(ki, qi), 0)

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_q_blocks=num_q_blocks,
    )
    dk_per_qh, dv_per_qh = pl.pallas_call(
        dkv_kernel,
        grid=(batch, num_heads, num_k_blocks, num_q_blocks),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, head_dim), q_map2),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_map2),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_map2),
            pl.BlockSpec((1, 1, block_q, head_dim), q_map2),
            pl.BlockSpec((1, 1, block_q, 1), row_map2),
            pl.BlockSpec((1, 1, block_q, 1), row_map2),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, head_dim), kv_out_map),
            pl.BlockSpec((1, 1, block_k, head_dim), kv_out_map),
        ],
        out_shape=[
            _sds((batch, num_heads, seq_k, head_dim), q.dtype,
                 _vma(q, k, v, do)),
            _sds((batch, num_heads, seq_k, head_dim), q.dtype,
                 _vma(q, k, v, do)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, head_dim), jnp.float32),
            pltpu.VMEM((block_k, head_dim), jnp.float32),
        ],
        compiler_params=_DIM_SEMANTICS,
        interpret=_use_interpret(),
    )(q, k, v, do, lse, delta)

    if group > 1:
        dk = dk_per_qh.reshape(
            batch, num_kv_heads, group, seq_k, head_dim
        ).sum(axis=2).astype(k.dtype)
        dv = dv_per_qh.reshape(
            batch, num_kv_heads, group, seq_k, head_dim
        ).sum(axis=2).astype(v.dtype)
    else:
        dk, dv = dk_per_qh, dv_per_qh
    return dq, dk, dv


# ===========================================================================
# Public API
# ===========================================================================


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 1024,
    block_k: int = 1024,
) -> jax.Array:
    """Blockwise attention: softmax(q k^T / sqrt(d)) v.

    Args:
      q: (batch, num_heads, seq_q, head_dim)
      k/v: (batch, num_kv_heads, seq_k, head_dim); num_heads must be a
        multiple of num_kv_heads (GQA/MQA).
    """
    out, _ = _flash_fwd(q, k, v, _scale(sm_scale, q), causal,
                        block_q, block_k)
    return out


def _scale(sm_scale: Optional[float], q: jax.Array) -> float:
    return sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])


def _fa_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd(q, k, v, _scale(sm_scale, q), causal,
                          block_q, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, sm_scale, block_q, block_k, res, g):
    q = res[0]
    dq, dk, dv = _flash_bwd(res, g, sm_scale=_scale(sm_scale, q),
                            causal=causal, block_q=block_q, block_k=block_k)
    return dq, dk, dv


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def reference_attention(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None) -> jax.Array:
    """Plain-XLA attention with identical semantics (test oracle and
    small-shape fallback)."""
    scale = _scale(sm_scale, q)
    num_heads, num_kv_heads = q.shape[1], k.shape[1]
    if num_kv_heads != num_heads:
        reps = num_heads // num_kv_heads
        k = jnp.repeat(k, reps, axis=1)
        v = jnp.repeat(v, reps, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        seq_q, seq_k = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((seq_q, seq_k), dtype=bool))
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def mesh_flash_attention(q, k, v, causal: bool = True,
                         sm_scale: Optional[float] = None) -> jax.Array:
    """flash_attention partitioned over the ambient mesh.

    A Pallas kernel is a custom call the SPMD partitioner cannot split on
    real TPU, so under a multi-device mesh it must run inside a shard_map
    that makes the batch/head axes manual: batch over (data, fsdp), heads
    over tensor — each device runs the kernel on its local block. Falls
    back to the plain call when there is no ambient mesh (single chip),
    when no relevant axis is >1, or when the shapes don't divide (XLA
    then reports the partitioning failure loudly rather than silently
    replicating)."""
    from jax.sharding import PartitionSpec as P

    from dlrover_tpu.common.constants import MeshAxis
    from dlrover_tpu.parallel.mesh import current_mesh

    mesh = current_mesh()
    if mesh is None:
        return flash_attention(q, k, v, causal, sm_scale)
    # Inside an already-manual region (e.g. the pipeline's pipe-manual
    # shard_map) a nested full-mesh shard_map cannot be traced (mesh
    # mismatch / interpret-mode carry typing) — call the kernel directly;
    # its operands there are the caller's per-shard blocks.
    if _vma(q, k, v):
        return flash_attention(q, k, v, causal, sm_scale)
    # foreign ambient meshes (no data/fsdp/tensor axes) fall through to
    # the plain call via the dp == tp == 1 check
    dp = (mesh.shape.get(MeshAxis.DATA, 1)
          * mesh.shape.get(MeshAxis.FSDP, 1))
    tp = mesh.shape.get(MeshAxis.TENSOR, 1)
    if dp == 1 and tp == 1:
        return flash_attention(q, k, v, causal, sm_scale)
    if (q.shape[0] % dp or q.shape[1] % tp or k.shape[1] % tp):
        return flash_attention(q, k, v, causal, sm_scale)
    spec = P((MeshAxis.DATA, MeshAxis.FSDP), MeshAxis.TENSOR, None, None)
    fn = shard_map(
        lambda a, b, c: flash_attention(a, b, c, causal, sm_scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
