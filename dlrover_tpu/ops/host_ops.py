"""Host-side native custom ops + the jit integration pattern.

Capability parity: tfplus's custom-op extension point (the reference's
`tfplus/tfplus/cc/demo.{h,cc}` skeleton + its Bazel/setup.py build —
tfplus/setup.py:155). TPU re-design: device custom ops are Pallas kernels
(ops/flash_attention.py, ops/quantization.py); HOST custom ops are
C-linkage functions in native/custom_op.cpp loaded via ctypes, and
`checksum_in_jit` shows the sanctioned way to call one from inside a jit
program (jax.pure_callback with a declared abstract result — XLA treats it
as an opaque host call; do NOT put these on the hot path, they force a
device→host sync).

Both ops degrade to numpy when the native toolchain is unavailable, so the
data plane never hard-depends on g++ at runtime.
"""

from __future__ import annotations

import ctypes
import zlib
from typing import Tuple

import jax
import numpy as np

from dlrover_tpu.native_build import load_native


def _as_bytes_view(data) -> np.ndarray:
    arr = np.ascontiguousarray(data)
    return arr.view(np.uint8).reshape(-1)


def crc32(data, seed: int = 0) -> int:
    """zlib-compatible CRC32 of an array/bytes; chain via `seed`."""
    view = _as_bytes_view(np.frombuffer(data, np.uint8)
                          if isinstance(data, (bytes, bytearray))
                          else data)
    lib = load_native()
    if lib is None or not hasattr(lib, "dlrover_tpu_crc32"):
        return zlib.crc32(view.tobytes(), seed) & 0xFFFFFFFF
    ptr = view.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    return int(lib.dlrover_tpu_crc32(ptr, view.size, seed & 0xFFFFFFFF))


def token_histogram(tokens, vocab_size: int,
                    count_oov: bool = True) -> Tuple[np.ndarray, int]:
    """Counts of each token id; returns (hist, n_out_of_vocab).

    hist has vocab_size+1 slots when count_oov (last slot = OOV bucket),
    else vocab_size. Used by the data plane for input-skew diagnostics.
    """
    toks = np.ascontiguousarray(tokens, dtype=np.int32).reshape(-1)
    slots = vocab_size + (1 if count_oov else 0)
    hist = np.zeros(slots, dtype=np.uint64)
    lib = load_native()
    if lib is None or not hasattr(lib, "dlrover_tpu_token_histogram"):
        in_vocab = toks[(toks >= 0) & (toks < vocab_size)]
        hist[:vocab_size] += np.bincount(
            in_vocab, minlength=vocab_size).astype(np.uint64)
        oov = toks.size - in_vocab.size
        if count_oov:
            hist[vocab_size] += np.uint64(oov)
        return hist, int(oov)
    oov = lib.dlrover_tpu_token_histogram(
        toks.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), toks.size,
        hist.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)), vocab_size,
        1 if count_oov else 0)
    return hist, int(oov)


def checksum_in_jit(x: jax.Array) -> jax.Array:
    """CRC32 of a device array from INSIDE a jit program — the extension-
    point demo: jax.pure_callback bridges a traced value to the native op
    and back as a declared uint32 scalar."""
    def _host(arr) -> np.ndarray:
        return np.uint32(crc32(np.asarray(arr)))

    return jax.pure_callback(
        _host, jax.ShapeDtypeStruct((), np.uint32), x, vmap_method="sequential")
