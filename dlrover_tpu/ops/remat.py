"""Named rematerialization policies (consumed by model configs and
the checkpoint/remat optimization; reference analog: atorch
activation_checkpointing.py policy selection)."""


def resolve_remat_policy(name: str):
    """Named rematerialization policy → jax.checkpoint_policies member.
    "full"/"nothing_saveable" recomputes everything; "dots"/"dots_saveable"
    keeps matmul outputs (cheaper backward, more memory)."""
    import jax

    policies = {
        "": jax.checkpoint_policies.nothing_saveable,
        "full": jax.checkpoint_policies.nothing_saveable,
        "nothing_saveable": jax.checkpoint_policies.nothing_saveable,
        "dots": jax.checkpoint_policies.dots_saveable,
        "dots_saveable": jax.checkpoint_policies.dots_saveable,
        "dots_with_no_batch_dims":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }
    if name not in policies:
        raise ValueError(f"unknown remat policy {name!r}; "
                         f"choose from {sorted(policies)}")
    return policies[name]


