"""TPU-native kernels (Pallas) + reference implementations.

Capability parity with the reference's native-op layer (SURVEY.md §2.3):
- flash attention  ≙ atorch flash-attn integration
  (atorch/modules/transformer/layers.py FA modules) — here a Pallas TPU
  kernel with custom VJP
- fused norms      ≙ atorch/normalization/layernorm.py (apex fused LN)
- quantization     ≙ atorch/ops/csrc/{quantize,dequantize,...}.cu
"""

from dlrover_tpu.ops.flash_attention import flash_attention
from dlrover_tpu.ops.norms import fused_rms_norm
