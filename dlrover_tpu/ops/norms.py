"""Fused RMSNorm / LayerNorm Pallas kernels.

TPU-native equivalent of the reference's fused normalization
(atorch/atorch/normalization/layernorm.py:157-237, an apex-CUDA-backed
autograd function): one VMEM-resident kernel per (rows-block), fp32 math,
custom VJP with a fused backward.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from dlrover_tpu.common.jax_compat import shape_dtype_struct
from dlrover_tpu.ops.flash_attention import _vma


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _rms_fwd_kernel(x_ref, w_ref, o_ref, rstd_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    var = jnp.mean(x * x, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    o_ref[:] = (x * rstd * w).astype(o_ref.dtype)
    rstd_ref[:] = rstd


def _rms_bwd_kernel(x_ref, w_ref, rstd_ref, g_ref, dx_ref, dwp_ref,
                    *, eps: float):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dwp_ref[:] = jnp.zeros_like(dwp_ref)

    x = x_ref[:].astype(jnp.float32)
    w = w_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    rstd = rstd_ref[:]
    xhat = x * rstd
    wg = g * w
    # dx = rstd * (wg - xhat * mean(wg * xhat))
    mean_term = jnp.mean(wg * xhat, axis=-1, keepdims=True)
    dx_ref[:] = (rstd * (wg - xhat * mean_term)).astype(dx_ref.dtype)
    # dw accumulates into a single (8, dim) block across the sequential
    # grid; the partial is split evenly over 8 sublanes (exact: /8) and the
    # caller sums the rows.
    partial = jnp.sum(g * xhat, axis=0, keepdims=True) * 0.125
    dwp_ref[:] += jnp.broadcast_to(partial, dwp_ref.shape)


def _rows_block(n_rows: int, dim: int, bytes_per_elem: int) -> int:
    """Row-block size: a divisor of n_rows (Pallas pads out-of-bounds
    rows with undefined data on real TPU, and the backward's dw
    accumulation would silently fold that garbage into the weight
    gradient), capped so the block's fp32 working set fits scoped VMEM.
    bytes_per_elem estimates the live per-element footprint — ~12 B for
    the forward (x, out, fp32 copy), ~32 B for the backward (x, g, dx,
    xhat, wg and products); 10 MB of the 16 MB scoped limit leaves
    headroom for the weight row and rstd column."""
    from dlrover_tpu.ops.flash_attention import fit_block

    cap = max(8, (10 * 1024 * 1024) // (dim * bytes_per_elem))
    return fit_block(n_rows, min(256, cap))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fused_rms_norm(x: jax.Array, weight: jax.Array,
                   eps: float = 1e-6) -> jax.Array:
    """RMSNorm over the last dim: x * rsqrt(mean(x^2) + eps) * weight.

    Accepts any leading shape; rows are processed in VMEM blocks.
    """
    out, _ = _rms_fwd(x, weight, eps)
    return out


def _rms_fwd(x, weight, eps):
    orig_shape = x.shape
    dim = orig_shape[-1]
    x2 = x.reshape(-1, dim)
    rows = x2.shape[0]
    block = _rows_block(rows, dim, bytes_per_elem=12)
    grid = ((rows + block - 1) // block,)
    out, rstd = pl.pallas_call(
        functools.partial(_rms_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, dim), lambda i: (i, 0)),
            pl.BlockSpec((dim,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((block, dim), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            shape_dtype_struct(x2.shape, x.dtype, vma=_vma(x2, weight)),
            shape_dtype_struct((rows, 1), jnp.float32,
                               vma=_vma(x2, weight)),
        ],
        interpret=_use_interpret(),
    )(x2, weight)
    return out.reshape(orig_shape), (x2, weight, rstd, orig_shape)


def _rms_fwd_vjp(x, weight, eps):
    return _rms_fwd(x, weight, eps)


def _rms_bwd_vjp(eps, res, g):
    x2, weight, rstd, orig_shape = res
    dim = x2.shape[1]
    rows = x2.shape[0]
    g2 = g.reshape(-1, dim)
    block = _rows_block(rows, dim, bytes_per_elem=32)
    n_blocks = (rows + block - 1) // block
    dx, dw_partial = pl.pallas_call(
        functools.partial(_rms_bwd_kernel, eps=eps),
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((block, dim), lambda i: (i, 0)),
            pl.BlockSpec((dim,), lambda i: (0,)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
            pl.BlockSpec((block, dim), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block, dim), lambda i: (i, 0)),
            pl.BlockSpec((8, dim), lambda i: (0, 0)),
        ],
        out_shape=[
            shape_dtype_struct(x2.shape, x2.dtype,
                               vma=_vma(x2, weight, g2)),
            shape_dtype_struct((8, dim), jnp.float32,
                               vma=_vma(x2, weight, g2)),
        ],
        interpret=_use_interpret(),
    )(x2, weight, rstd, g2)
    dw = dw_partial.sum(axis=0).astype(weight.dtype)
    return dx.reshape(orig_shape), dw


fused_rms_norm.defvjp(_rms_fwd_vjp, _rms_bwd_vjp)


def reference_rms_norm(x, weight, eps: float = 1e-6):
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps) * weight).astype(x.dtype)
