"""Quantization kernel suite: int8/int4 groupwise quantize / dequantize /
swizzled layouts / quantized reduction.

Capability parity: the reference's CUDA quantization library
(atorch/atorch/ops/csrc/: quantize.cu:150, dequantize.cu:67,
swizzled_quantize.cu:194, quant_reduce.cu:248, pt_binding.cpp:178 and the
vectorized memory_access/conversion/reduction headers). TPU re-design:
- groupwise symmetric quantization as a Pallas kernel (VMEM-resident
  rows, fp32 scale math) with an XLA reference path;
- "swizzle" = the partner-major tile re-layout used before chunked
  collectives (the CUDA version reorders for coalesced NVLink pushes;
  here the permutation is a cheap XLA reshape/transpose the compiler
  fuses into the collective's copy);
- quant_reduce = dequantize-accumulate-requantize across chunks, the
  compressed-gradient all-reduce building block.

int4 values are carried two-per-int8 (packed low/high nibble), matching
the CUDA suite's storage.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _use_interpret() -> bool:
    return jax.default_backend() != "tpu"


def _qmax(bits: int) -> int:
    if bits == 8:
        return 127
    if bits == 4:
        return 7
    raise ValueError(f"bits must be 4 or 8, got {bits}")


# ---------------------------------------------------------------------------
# Pallas kernels (int8 path; int4 packs outside the kernel)
# ---------------------------------------------------------------------------


def _quantize_kernel(x_ref, q_ref, scale_ref, *, qmax: int):
    x = x_ref[:].astype(jnp.float32)          # (rows_block, group)
    absmax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = absmax / qmax
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(jnp.round(x * inv), -qmax, qmax)
    q_ref[:] = q.astype(jnp.int8)
    scale_ref[:] = scale


def _dequantize_kernel(q_ref, scale_ref, o_ref):
    o_ref[:] = (q_ref[:].astype(jnp.float32)
                * scale_ref[:]).astype(o_ref.dtype)


def _rows_block(rows: int) -> int:
    return min(rows, 512)


def quantize(x: jax.Array, bits: int = 8, group_size: int = 128
             ) -> Tuple[jax.Array, jax.Array]:
    """Groupwise symmetric quantization over the last dim.

    Returns (q, scales): q int8 — for bits=4, two nibbles packed per int8,
    so the last dim halves; scales fp32 with shape x.shape[:-1] +
    (groups,).
    """
    qmax = _qmax(bits)
    orig_shape = x.shape
    if orig_shape[-1] % group_size:
        raise ValueError(
            f"last dim {orig_shape[-1]} not divisible by group "
            f"{group_size}")
    groups = orig_shape[-1] // group_size
    x2 = x.reshape(-1, group_size)            # (rows, group)
    rows = x2.shape[0]
    block = _rows_block(rows)
    grid = ((rows + block - 1) // block,)
    q, scales = pl.pallas_call(
        functools.partial(_quantize_kernel, qmax=qmax),
        grid=grid,
        in_specs=[pl.BlockSpec((block, group_size), lambda i: (i, 0))],
        out_specs=[
            pl.BlockSpec((block, group_size), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, group_size), jnp.int8),
            jax.ShapeDtypeStruct((rows, 1), jnp.float32),
        ],
        interpret=_use_interpret(),
    )(x2)
    scales = scales.reshape(orig_shape[:-1] + (groups,))
    q = q.reshape(orig_shape)
    if bits == 4:
        q = pack_int4(q)
    return q, scales


def dequantize(q: jax.Array, scales: jax.Array, bits: int = 8,
               dtype=jnp.float32) -> jax.Array:
    """Inverse of `quantize`."""
    if bits == 4:
        q = unpack_int4(q)
    orig_shape = q.shape
    groups = scales.shape[-1]
    group_size = orig_shape[-1] // groups
    q2 = q.reshape(-1, group_size)
    s2 = scales.reshape(-1, 1)
    rows = q2.shape[0]
    block = _rows_block(rows)
    grid = ((rows + block - 1) // block,)
    out = pl.pallas_call(
        _dequantize_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block, group_size), lambda i: (i, 0)),
            pl.BlockSpec((block, 1), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((block, group_size), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, group_size), dtype),
        interpret=_use_interpret(),
    )(q2, s2)
    return out.reshape(orig_shape)


# ---------------------------------------------------------------------------
# int4 packing
# ---------------------------------------------------------------------------


def pack_int4(q: jax.Array) -> jax.Array:
    """int8 values in [-7, 7] → packed nibbles, last dim halves."""
    lo = q[..., 0::2] & 0x0F
    hi = (q[..., 1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def unpack_int4(packed: jax.Array) -> jax.Array:
    """Packed nibbles → int8 values (sign-extended), last dim doubles."""
    lo = (packed & 0x0F).astype(jnp.int8)
    lo = jnp.where(lo > 7, lo - 16, lo)
    hi = ((packed.astype(jnp.int32) >> 4) & 0x0F).astype(jnp.int8)
    hi = jnp.where(hi > 7, hi - 16, hi)
    out = jnp.stack([lo, hi], axis=-1)
    return out.reshape(packed.shape[:-1] + (packed.shape[-1] * 2,))


# ---------------------------------------------------------------------------
# Swizzled quantize + quantized reduction
# ---------------------------------------------------------------------------


def swizzled_quantize(x: jax.Array, partners: int, bits: int = 8,
                      group_size: int = 128
                      ) -> Tuple[jax.Array, jax.Array]:
    """Quantize then re-layout partner-major for chunked collectives.

    x flat length must divide by partners×group_size. Output q has shape
    (partners, chunk): partner p's chunk is contiguous, so a
    reduce-scatter/all-to-all sends one dense slice per peer (the CUDA
    swizzled_quantize.cu serves the same purpose for NVLink pushes).
    """
    flat = x.reshape(-1)
    if flat.shape[0] % (partners * group_size):
        raise ValueError("size not divisible by partners*group_size")
    chunk = flat.shape[0] // partners
    # interleaved → partner-major: element i goes to partner i % partners
    swizzled = flat.reshape(chunk, partners).T.reshape(partners, chunk)
    q, scales = quantize(swizzled, bits=bits, group_size=group_size)
    return q, scales


def unswizzle_dequantize(q: jax.Array, scales: jax.Array, shape,
                         bits: int = 8, dtype=jnp.float32) -> jax.Array:
    partners = q.shape[0]
    deq = dequantize(q, scales, bits=bits, dtype=dtype)
    flat = deq.reshape(partners, -1).T.reshape(-1)
    return flat.reshape(shape)


def quant_reduce(qs: jax.Array, scales: jax.Array, bits: int = 8,
                 group_size: int = 128) -> Tuple[jax.Array, jax.Array]:
    """Reduce N quantized chunks → one re-quantized chunk.

    qs: (N, ...) packed ints; scales: (N, ..., groups). Dequantize each,
    accumulate in fp32, requantize (the CUDA quant_reduce.cu pipeline for
    hierarchical compressed all-reduce).
    """
    deq = jax.vmap(lambda q, s: dequantize(q, s, bits=bits))(qs, scales)
    total = jnp.sum(deq, axis=0)
    return quantize(total, bits=bits, group_size=group_size)


# ---------------------------------------------------------------------------
# XLA reference (test oracle)
# ---------------------------------------------------------------------------


def reference_quantize(x: jax.Array, bits: int = 8, group_size: int = 128
                       ) -> Tuple[jax.Array, jax.Array]:
    qmax = _qmax(bits)
    orig = x.shape
    x2 = x.reshape(-1, group_size).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = absmax / qmax
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(jnp.round(x2 * inv), -qmax, qmax).astype(jnp.int8)
    q = q.reshape(orig)
    scales = scale.reshape(orig[:-1] + (orig[-1] // group_size,))
    if bits == 4:
        q = pack_int4(q)
    return q, scales
