"""Device prefetch: overlap host batch prep with device compute.

Capability parity: atorch data/preloader.py (CUDA-stream prefetch). TPU
re-design: `jax.device_put` is async — keeping `depth` batches in flight
overlaps the host→HBM DMA of batch i+1 with the step on batch i (the
stream role is played by XLA's async dispatch).
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Iterable, Iterator, Optional

import jax


def prefetch_to_device(
    iterator: Iterable,
    depth: int = 2,
    sharding: Optional[Any] = None,
    transform: Optional[Callable] = None,
) -> Iterator:
    """Yield batches already on device, `depth` ahead of consumption."""
    queue: collections.deque = collections.deque()

    def put(batch):
        if transform is not None:
            batch = transform(batch)
        if sharding is not None:
            return jax.tree.map(
                lambda x: jax.device_put(x, sharding), batch)
        return jax.tree.map(jax.device_put, batch)

    it = iter(iterator)
    for batch in it:
        queue.append(put(batch))
        if len(queue) >= depth:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
