"""Device prefetch: overlap host batch prep with device compute.

Capability parity: atorch data/preloader.py (CUDA-stream prefetch). TPU
re-design: `jax.device_put` is async — keeping `depth` batches in flight
overlaps the host→HBM DMA of batch i+1 with the step on batch i (the
stream role is played by XLA's async dispatch).

`PrefetchAutoTuner` closes the loop from the step timeline: when the
windowed ``data_wait`` fraction (obs/timeline.py) says the step loop is
starving on input, the recommended depth grows toward
``ctx.prefetch_depth_max``; when the pipeline stops starving it decays
back so idle device buffers don't pin HBM. Recommendations are advisory
and consumed at (re)build boundaries — passing ``tuner.depth_fn`` as
``depth`` makes an existing prefetch loop pick up changes batch-to-batch
without a rebuild.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Iterable, Iterator, Optional, Union

import jax


def prefetch_to_device(
    iterator: Iterable,
    depth: Union[int, Callable[[], int]] = 2,
    sharding: Optional[Any] = None,
    transform: Optional[Callable] = None,
) -> Iterator:
    """Yield batches already on device, `depth` ahead of consumption.

    ``depth`` may be a callable (e.g. ``PrefetchAutoTuner.depth_fn``):
    it is re-read each batch, so an auto-tuned depth change applies to
    the in-flight window without rebuilding the pipeline. A shrink
    drains naturally — queued batches are yielded, never dropped.
    """
    queue: collections.deque = collections.deque()
    depth_fn = depth if callable(depth) else (lambda: depth)

    def put(batch):
        if transform is not None:
            batch = transform(batch)
        if sharding is not None:
            return jax.tree.map(
                lambda x: jax.device_put(x, sharding), batch)
        return jax.tree.map(jax.device_put, batch)

    it = iter(iterator)
    for batch in it:
        queue.append(put(batch))
        if len(queue) >= max(1, int(depth_fn())):
            yield queue.popleft()
    while queue:
        yield queue.popleft()


class PrefetchAutoTuner:
    """data_wait-driven depth/ring sizing (knob: ctx.prefetch_autotune).

    Fed once per report window by the step loop
    (ElasticTrainLoop._report_progress) with the timeline's windowed
    ``data_wait_fraction``. Asymmetric on purpose: growth is immediate
    (a starving device is paying real badput every step) while shrink
    requires two consecutive calm windows (a single fast window after a
    refill must not thrash the depth back down).
    """

    # shrink only below this fraction of the grow trigger — the dead
    # band between shrink and grow is the hysteresis that stops a
    # pipeline sitting near the threshold from oscillating
    _SHRINK_FRACTION = 0.25
    _SHRINK_CALM_WINDOWS = 2

    def __init__(self, depth: int = 2,
                 depth_min: Optional[int] = None,
                 depth_max: Optional[int] = None,
                 wait_threshold: Optional[float] = None):
        from dlrover_tpu.common.config import Context

        ctx = Context.singleton()
        self._min = int(depth_min if depth_min is not None
                        else ctx.prefetch_depth_min)
        self._max = int(depth_max if depth_max is not None
                        else ctx.prefetch_depth_max)
        self._threshold = float(wait_threshold if wait_threshold is not None
                                else ctx.data_wait_tune_fraction)
        self._lock = threading.Lock()
        self._depth = max(self._min, min(self._max, int(depth)))
        self._calm_windows = 0

    @property
    def depth(self) -> int:
        with self._lock:
            return self._depth

    def depth_fn(self) -> int:
        """Bound method handed to ``prefetch_to_device(depth=...)``."""
        return self.depth

    def observe(self, data_wait_fraction: float) -> int:
        """One report window's data-wait evidence; returns the (possibly
        updated) recommended depth. Negative fractions mean "no timeline
        evidence" and change nothing."""
        if data_wait_fraction < 0.0:
            return self.depth
        with self._lock:
            if data_wait_fraction > self._threshold:
                self._calm_windows = 0
                if self._depth < self._max:
                    self._depth += 1
            elif data_wait_fraction < self._threshold * self._SHRINK_FRACTION:
                self._calm_windows += 1
                if (self._calm_windows >= self._SHRINK_CALM_WINDOWS
                        and self._depth > self._min):
                    self._depth -= 1
                    self._calm_windows = 0
            else:
                self._calm_windows = 0
            return self._depth

    def ring_capacity(self, base_capacity: int = 64 << 20) -> int:
        """Recommended ShmDataContext ring capacity for the current
        depth: scaled from the default-depth baseline so a deeper
        prefetch window never stalls its producers on ring backpressure.
        Advisory — consumed when a ring is (re)built, never live."""
        with self._lock:
            scale = max(1, self._depth) / 2.0
        return int(base_capacity * max(1.0, scale))
