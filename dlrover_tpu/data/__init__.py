"""Data pipeline: device prefetch, shared-memory coworker IPC, services.

Capability parity: atorch/data/ —
- `prefetch_to_device` ≙ data/preloader.py (CUDA-stream prefetch → here
  double-buffered async device_put)
- `ShmRing`/`ShmDataContext` ≙ data/shm_context.py:139 (C++ ring, ctypes)
- `CoworkerDataService` ≙ atorch/service/coworker_data_service.py (gRPC
  batches from CPU pods)
- `ElasticDataLoader` lives in dlrover_tpu/trainer/dataloader.py
"""

from dlrover_tpu.data.prefetch import PrefetchAutoTuner, prefetch_to_device
from dlrover_tpu.data.shm_ring import ShmDataContext, ShmRing

__all__ = ["PrefetchAutoTuner", "prefetch_to_device", "ShmDataContext",
           "ShmRing"]
