"""Coworker data plane: CPU pods push preprocessed batches to TPU pods.

Capability parity: atorch/service/coworker_data_service.py +
data_info_service.py + rpc_clients.py (gRPC services connecting GPU pods
to CPU "coworker" preprocessing pods; protos/coworker.proto) and
CoworkerDataset (data/coworker_dataset.py:13). Same 2-RPC comm layer as
the control plane; same-host coworkers should prefer ShmDataContext (no
serialization), this service is the cross-host path.
"""

from __future__ import annotations

import pickle
import queue
import threading
from typing import Any, Iterator, Optional

import grpc

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.comm import MasterStub, build_channel, build_server
from dlrover_tpu.common.log import default_logger as logger


class CoworkerDataService:
    """Runs INSIDE the trainer process; coworkers dial it and push
    batches. Bounded queue: producers see back-pressure via CoworkerInfo
    and blocked reports."""

    def __init__(self, capacity: int = 64, port: int = 0,
                 host: str = "0.0.0.0"):
        self._queues: dict = {}
        self._capacity = capacity
        # single False->True transition read from RPC threads and
        # the trainer's consumer loop: an Event, not a bare bool
        self._finished = threading.Event()
        self._lock = threading.Lock()
        self._server, self.port = build_server(
            self._get_bytes, self._report_bytes, port=port, host=host)

    def start(self) -> None:
        self._server.start()
        logger.info("coworker data service on port %d", self.port)

    def stop(self, grace_s: float = 0.5) -> None:
        self._server.stop(grace_s)

    def _queue_for(self, dataset: str) -> "queue.Queue":
        with self._lock:
            if dataset not in self._queues:
                self._queues[dataset] = queue.Queue(self._capacity)
            return self._queues[dataset]

    # -- wire ------------------------------------------------------------
    def _get_bytes(self, payload: bytes,
                   context: grpc.ServicerContext) -> bytes:
        request = msg.deserialize_message(payload)
        if isinstance(request, msg.CoworkerBatchRequest):
            q = self._queue_for(request.dataset_name)
            # queued/capacity are the data_info back-off contract:
            # consumed by out-of-repo coworker runners pacing their
            # push loops, not by anything in this package
            return msg.serialize_message(msg.CoworkerInfo(  # graftlint: disable=GL401
                dataset_name=request.dataset_name,
                queued=q.qsize(), capacity=self._capacity,
                finished=self._finished.is_set(),
            ))
        return msg.serialize_message(
            msg.Response(success=False, reason="unknown request"))

    def _report_bytes(self, payload: bytes,
                      context: grpc.ServicerContext) -> bytes:
        request = msg.deserialize_message(payload)
        if isinstance(request, msg.CoworkerBatch):
            try:
                self._queue_for(request.dataset_name).put(
                    request.payload, timeout=20.0)
                return msg.serialize_message(msg.Response(success=True))
            except queue.Full:
                return msg.serialize_message(msg.Response(
                    success=False, reason="queue full"))
        return msg.serialize_message(
            msg.Response(success=False, reason="unknown request"))

    # -- trainer-side consumption ----------------------------------------
    def mark_finished(self) -> None:
        self._finished.set()

    def batches(self, dataset_name: str = "default",
                timeout_s: Optional[float] = 60.0) -> Iterator[Any]:
        import time

        q = self._queue_for(dataset_name)
        last_progress = time.time()
        while True:
            try:
                payload = q.get(timeout=0.2)
                last_progress = time.time()
                yield pickle.loads(payload)
            except queue.Empty:
                if self._finished.is_set():
                    return
                if (timeout_s is not None
                        and time.time() - last_progress > timeout_s):
                    raise TimeoutError(
                        f"no coworker batch for dataset "
                        f"{dataset_name!r} in {timeout_s:.0f}s")


class CoworkerClient:
    """Runs in the CPU coworker process; pushes batches with back-off."""

    def __init__(self, trainer_addr: str, producer_id: int = 0,
                 timeout_s: float = 30.0):
        self._stub = MasterStub(build_channel(trainer_addr))
        self._producer_id = producer_id
        self._timeout_s = timeout_s
        self._seq = 0

    def queue_info(self, dataset_name: str = "default") -> msg.CoworkerInfo:
        raw = self._stub.get(msg.serialize_message(
            msg.CoworkerBatchRequest(dataset_name=dataset_name)),
            timeout=self._timeout_s)
        return msg.deserialize_message(raw)

    def push_batch(self, batch: Any, dataset_name: str = "default") -> bool:
        self._seq += 1
        # producer_id/seq stamp the wire for duplicate/ordering
        # forensics on multi-producer setups; the service consumes
        # payload only by design (queue order is the contract)
        record = msg.CoworkerBatch(  # graftlint: disable=GL401
            dataset_name=dataset_name,
            payload=pickle.dumps(batch,
                                 protocol=pickle.HIGHEST_PROTOCOL),
            producer_id=self._producer_id,
            seq=self._seq,
        )
        raw = self._stub.report(msg.serialize_message(record),
                                timeout=self._timeout_s)
        response = msg.deserialize_message(raw)
        return bool(getattr(response, "success", False))
