"""Shared-memory ring: Python surface over the C++ core.

Capability parity: atorch ShmDataContext (atorch/data/shm_context.py:139)
+ CoworkerDataset (data/coworker_dataset.py:13) — CPU preprocessing
processes push pickled/raw batches into per-worker rings; the training
process pops without socket serialization. Falls back to a pure-Python
ring (multiprocessing.shared_memory) when the native toolchain is absent.
"""

from __future__ import annotations

import ctypes
import pickle
import struct
import time
import uuid
from typing import Any, Iterator, List, Optional

from dlrover_tpu.native_build import load_native


class RingClosed(Exception):
    pass


class RingTimeout(TimeoutError):
    pass


class ShmRing:
    """Single-producer single-consumer byte-record ring."""

    def __init__(self, name: Optional[str] = None,
                 capacity: int = 64 << 20, owner: bool = True,
                 _force_fallback: bool = False):
        self.name = name or f"/dlrover-tpu-{uuid.uuid4().hex[:12]}"
        if not self.name.startswith("/"):
            self.name = "/" + self.name
        self._owner = owner
        self._closed = False
        self._lib = None if _force_fallback else load_native()
        if self._lib is not None:
            self._handle = self._lib.shm_ring_open(
                self.name.encode(), capacity, 1 if owner else 0)
            if not self._handle:
                raise OSError(f"shm_ring_open failed for {self.name}")
        else:
            self._fallback = _PyRing(self.name, capacity, owner)

    # -- byte records --------------------------------------------------
    def push_bytes(self, payload: bytes,
                   timeout_s: Optional[float] = 30.0) -> None:
        timeout_ms = -1 if timeout_s is None else int(timeout_s * 1000)
        if self._lib is not None:
            buf = (ctypes.c_uint8 * len(payload)).from_buffer_copy(payload)
            code = self._lib.shm_ring_push(self._handle, buf, len(payload),
                                           timeout_ms)
            if code == -1:
                raise RingTimeout("push timed out")
            if code == -2:
                raise RingClosed()
            if code == -3:
                raise ValueError("record larger than ring capacity")
            return
        self._fallback.push(payload, timeout_ms)

    def pop_bytes(self, timeout_s: Optional[float] = 30.0) -> bytes:
        timeout_ms = -1 if timeout_s is None else int(timeout_s * 1000)
        if self._lib is not None:
            deadline = time.time() + (timeout_s or 0)
            while True:
                length = self._lib.shm_ring_next_len(self._handle)
                if length == -2:
                    raise RingClosed()
                if length > 0:
                    buf = (ctypes.c_uint8 * length)()
                    got = self._lib.shm_ring_pop(self._handle, buf, length,
                                                 timeout_ms)
                    if got == -2:
                        raise RingClosed()
                    if got == -1:
                        raise RingTimeout("pop timed out")
                    if got < 0:
                        raise ValueError(
                            f"shm_ring_pop failed with code {got} "
                            "(concurrent consumers on one ring?)")
                    return bytes(bytearray(buf[:got]))
                if timeout_s is not None and time.time() > deadline:
                    raise RingTimeout("pop timed out")
                time.sleep(0.0005)
        return self._fallback.pop(timeout_ms)

    # -- python objects ------------------------------------------------
    def push(self, obj: Any, timeout_s: Optional[float] = 30.0) -> None:
        self.push_bytes(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL),
                        timeout_s)

    def pop(self, timeout_s: Optional[float] = 30.0) -> Any:
        return pickle.loads(self.pop_bytes(timeout_s))

    def mark_closed(self) -> None:
        if self._lib is not None:
            self._lib.shm_ring_mark_closed(self._handle)
        else:
            self._fallback.mark_closed()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._lib is not None:
            self._lib.shm_ring_close(self._handle)
        else:
            self._fallback.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class _PyRing:
    """Fallback ring over multiprocessing.shared_memory. Layout:
    magic u32 | pad u32 | head u64 | tail u64 | closed u64 | data. The
    magic distinguishes this layout from the native C++ one — the two are
    NOT interoperable, and attach refuses a layout mismatch instead of
    reading garbage offsets."""

    _HDR = 32
    _MAGIC = 0x50594c52          # "PYLR"
    _NATIVE_MAGIC = 0x444c5452   # the C++ ring's magic ("DLTR")

    def __init__(self, name: str, capacity: int, owner: bool):
        from multiprocessing import shared_memory

        self._capacity = capacity
        shm_name = name.strip("/")
        if owner:
            self._shm = shared_memory.SharedMemory(
                name=shm_name, create=True, size=self._HDR + capacity)
            self._shm.buf[:self._HDR] = b"\0" * self._HDR
            struct.pack_into("<I", self._shm.buf, 0, self._MAGIC)
        else:
            self._shm = shared_memory.SharedMemory(name=shm_name)
            (magic,) = struct.unpack_from("<I", self._shm.buf, 0)
            if magic == self._NATIVE_MAGIC:
                self._shm.close()
                raise RuntimeError(
                    f"ring {name!r} was created by the native C++ layout; "
                    "this process lacks the native library and cannot "
                    "attach (build it: python -m dlrover_tpu.native_build)")
            if magic != self._MAGIC:
                self._shm.close()
                raise RuntimeError(f"ring {name!r}: unknown layout magic "
                                   f"{magic:#x}")
            self._capacity = self._shm.size - self._HDR
        self._owner = owner

    def _get(self, idx: int) -> int:
        # slots: 1=head, 2=tail, 3=closed (slot 0 is magic+pad)
        return struct.unpack_from("<Q", self._shm.buf, idx * 8)[0]

    def _set(self, idx: int, value: int) -> None:
        struct.pack_into("<Q", self._shm.buf, idx * 8, value)

    def push(self, payload: bytes, timeout_ms: int) -> None:
        need = len(payload) + 4
        deadline = time.time() + timeout_ms / 1000.0
        cap = self._capacity
        if need + 4 > cap:
            raise ValueError("record larger than ring capacity")
        while True:
            if self._get(3):
                raise RingClosed()
            head, tail = self._get(1), self._get(2)
            pos = head % cap
            to_end = cap - pos
            effective = need if to_end >= need else to_end + need
            if cap - (head - tail) >= effective:
                base = self._HDR
                if to_end < need:
                    if to_end >= 4:
                        struct.pack_into("<I", self._shm.buf, base + pos,
                                         0xFFFFFFFF)
                    head += to_end
                    pos = 0
                struct.pack_into("<I", self._shm.buf, base + pos,
                                 len(payload))
                self._shm.buf[base + pos + 4:base + pos + 4 + len(payload)] \
                    = payload
                self._set(1, head + need)
                return
            if timeout_ms >= 0 and time.time() > deadline:
                raise RingTimeout("push timed out")
            time.sleep(0.001)

    def pop(self, timeout_ms: int) -> bytes:
        deadline = time.time() + timeout_ms / 1000.0
        cap = self._capacity
        base = self._HDR
        while True:
            head, tail = self._get(1), self._get(2)
            if head == tail:
                if self._get(3):
                    raise RingClosed()
                if timeout_ms >= 0 and time.time() > deadline:
                    raise RingTimeout("pop timed out")
                time.sleep(0.001)
                continue
            pos = tail % cap
            to_end = cap - pos
            if to_end < 4:
                self._set(2, tail + to_end)
                continue
            (length,) = struct.unpack_from("<I", self._shm.buf, base + pos)
            if length == 0xFFFFFFFF:
                self._set(2, tail + to_end)
                continue
            payload = bytes(
                self._shm.buf[base + pos + 4:base + pos + 4 + length])
            self._set(2, tail + length + 4)
            return payload

    def mark_closed(self) -> None:
        self._set(3, 1)

    def close(self) -> None:
        self._shm.close()
        if self._owner:
            try:
                self._shm.unlink()
            except FileNotFoundError:
                pass


class ShmDataContext:
    """N coworker→trainer rings + iterator (ShmDataContext analog).

    Trainer side: `context = ShmDataContext(num_rings, owner=True)`;
    pass `context.ring_names` to coworker processes, iterate
    `context.batches()`. Coworker side: `ShmDataContext.attach(names)`,
    `push(batch, ring_idx)`, `close_producers()` when exhausted.
    """

    def __init__(self, num_rings: int = 1, capacity: int = 64 << 20,
                 owner: bool = True,
                 ring_names: Optional[List[str]] = None):
        if ring_names is not None:
            self.rings = [ShmRing(name, capacity, owner=False)
                          for name in ring_names]
        else:
            self.rings = [ShmRing(capacity=capacity, owner=owner)
                          for _ in range(num_rings)]
        self.ring_names = [ring.name for ring in self.rings]

    @classmethod
    def attach(cls, ring_names: List[str],
               capacity: int = 64 << 20) -> "ShmDataContext":
        return cls(ring_names=ring_names, capacity=capacity)

    def push(self, batch: Any, ring_idx: int = 0,
             timeout_s: Optional[float] = 30.0) -> None:
        self.rings[ring_idx].push(batch, timeout_s)

    def close_producers(self) -> None:
        for ring in self.rings:
            ring.mark_closed()

    def batches(self, timeout_s: Optional[float] = 60.0) -> Iterator[Any]:
        """Round-robin over rings until all are closed and drained.
        Raises RingTimeout when no ring yields a batch for `timeout_s`
        (a dead producer that never called close_producers)."""
        live = list(self.rings)
        last_progress = time.time()
        while live:
            progressed = False
            for ring in list(live):
                try:
                    yield ring.pop(timeout_s=0.05)
                    progressed = True
                except RingTimeout:
                    continue
                except RingClosed:
                    live.remove(ring)
            if progressed:
                last_progress = time.time()
            elif live:
                if (timeout_s is not None
                        and time.time() - last_progress > timeout_s):
                    raise RingTimeout(
                        f"no batch from {len(live)} live ring(s) in "
                        f"{timeout_s:.0f}s (producer dead?)")
                time.sleep(0.005)

    def close(self) -> None:
        for ring in self.rings:
            ring.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
