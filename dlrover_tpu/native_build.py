"""Build/load the native library (shm ring + reconciler core).

`python -m dlrover_tpu.native_build` builds it explicitly; importers call
`load_native()` which builds on first use (g++ is in the image) and caches
the handle. Consumers degrade gracefully to pure-Python fallbacks when the
toolchain is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from dlrover_tpu.common.log import default_logger as logger

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SO_NAME = "libdlrover_tpu_native.so"

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def so_path() -> str:
    return os.path.join(_NATIVE_DIR, _SO_NAME)


def build(force: bool = False) -> bool:
    src_newer = False
    if os.path.exists(so_path()) and not force:
        so_mtime = os.path.getmtime(so_path())
        for name in os.listdir(_NATIVE_DIR):
            if name.endswith(".cpp"):
                if os.path.getmtime(
                        os.path.join(_NATIVE_DIR, name)) > so_mtime:
                    src_newer = True
        if not src_newer:
            return True
    try:
        subprocess.run(["make", "-C", _NATIVE_DIR], check=True,
                       capture_output=True, text=True, timeout=120)
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
            FileNotFoundError) as e:
        detail = getattr(e, "stderr", "") or str(e)
        logger.warning("native build failed: %s", detail[-2000:])
        return False


def load_native() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _load_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _load_failed:
            return None
        if not build():
            _load_failed = True
            return None
        try:
            lib = ctypes.CDLL(so_path())
        except OSError as e:
            logger.warning("native load failed: %s", e)
            _load_failed = True
            return None
        # -- shm ring signatures --
        lib.shm_ring_open.restype = ctypes.c_void_p
        lib.shm_ring_open.argtypes = [ctypes.c_char_p, ctypes.c_uint32,
                                      ctypes.c_int]
        lib.shm_ring_capacity.restype = ctypes.c_uint32
        lib.shm_ring_capacity.argtypes = [ctypes.c_void_p]
        lib.shm_ring_push.restype = ctypes.c_int
        lib.shm_ring_push.argtypes = [ctypes.c_void_p,
                                      ctypes.POINTER(ctypes.c_uint8),
                                      ctypes.c_uint32, ctypes.c_int64]
        lib.shm_ring_next_len.restype = ctypes.c_int64
        lib.shm_ring_next_len.argtypes = [ctypes.c_void_p]
        lib.shm_ring_pop.restype = ctypes.c_int64
        lib.shm_ring_pop.argtypes = [ctypes.c_void_p,
                                     ctypes.POINTER(ctypes.c_uint8),
                                     ctypes.c_uint32, ctypes.c_int64]
        lib.shm_ring_mark_closed.argtypes = [ctypes.c_void_p]
        lib.shm_ring_close.argtypes = [ctypes.c_void_p]
        # -- reconciler signatures --
        lib.reconciler_abi_version.restype = ctypes.c_int32
        # -- host custom ops (ops/host_ops.py); a stale .so may predate
        # them, and the shm/reconciler consumers must keep working then
        # (host_ops falls back to numpy via its own hasattr guard) --
        if hasattr(lib, "dlrover_tpu_crc32"):
            lib.dlrover_tpu_crc32.restype = ctypes.c_uint32
            lib.dlrover_tpu_crc32.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64,
                ctypes.c_uint32]
        if hasattr(lib, "dlrover_tpu_token_histogram"):
            lib.dlrover_tpu_token_histogram.restype = ctypes.c_uint64
            lib.dlrover_tpu_token_histogram.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32,
                ctypes.c_int]
        _lib = lib
        return _lib


def main() -> int:
    ok = build(force=True)
    print(f"native build: {'ok' if ok else 'FAILED'} ({so_path()})")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
