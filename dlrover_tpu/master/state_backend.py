"""Crash-consistent master state: a versioned, checksummed snapshot store.

The job master is the one component whose death previously killed the job:
rendezvous rounds, the node table, dataset task progress and the kv-store
lived only in ``JobMaster``'s memory. This module gives the master durable
control-plane state with the same guarantees a WAL-less embedded store can
offer from atomic-rename filesystems:

- **Atomicity**: every snapshot is written to a temp file in the same
  directory and ``os.replace``d into place — a crash mid-write leaves the
  previous snapshot intact, never a torn file.
- **Integrity**: the snapshot wrapper carries a SHA-256 over the canonical
  JSON of the state payload; ``load_latest`` verifies it and falls back to
  the next-older snapshot on mismatch (torn disk, bit rot, truncation).
- **Bounded retention**: only the newest ``retain`` snapshots are kept, so
  a long job cannot fill the state volume.

The store is deliberately schema-free (one JSON dict per snapshot); the
``JobMaster`` composes the dict from each component's ``export_state()``
and rebuilds them through ``restore_state()`` on restart — see
docs/fault_tolerance.md for the snapshot format and recovery sequence.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.log import default_logger as logger

_SNAPSHOT_RE = re.compile(r"^master-state-(\d{10})\.json$")
_FORMAT_VERSION = 1
MUTATION_LOG_NAME = "kv-mutlog.jsonl"


def _canonical(state: Dict[str, Any]) -> str:
    """Deterministic JSON for checksumming (and change detection)."""
    return json.dumps(state, sort_keys=True, separators=(",", ":"))


def _checksum(payload: str) -> str:
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


class SnapshotCorruptionError(RuntimeError):
    """A snapshot file failed its checksum / structure validation."""


class MutationLog:
    """Append-only log of the durable-worthy HOT mutations (the
    ``coord/`` barrier keys) between snapshots.

    Snapshots deliberately exclude the gradient-path keys from their
    TRIGGER set (a full export+fsync per training step would put storage
    in the step loop), so the barrier mutations land here instead: one
    JSON line per mutation, buffered writes, NO fsync. ``append`` is an
    in-memory ENQUEUE — a background drainer owns the disk, so the kv
    store's condition lock never waits on the (typically shared/NFS)
    state volume. The log is ROTATED (truncated) every time a snapshot
    is written, because the snapshot's state export includes the hot
    keys' values at that instant: replaying the (strictly newer) log
    over the latest snapshot is therefore always last-wins correct. A
    restarted master — or a promoted hot standby — replays it via
    ``KVStoreService.replay_mutations``.

    ``gate``: an optional callable the DRAINER consults before each
    write; truthy = this master has been fenced (a higher-generation
    master owns the lineage) and the entries are discarded instead of
    written. Checking on the drainer thread means fencing bites even
    when ONLY hot traffic is flowing (nothing else would run the
    fence check), and the check's own file read never runs under the
    kv lock.
    """

    def __init__(self, directory: str):
        self._path = os.path.join(directory, MUTATION_LOG_NAME)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # the open handle is OWNED by the drainer thread: every
        # write/flush happens outside the lock (kv mutations enqueue
        # under the kv condition — a disk stall here would be a
        # per-step stall, the PR 10 lesson GL501 now enforces).
        # rotate()/close() take the handle out under the lock after
        # quiescing the drainer and do their file work unlocked.
        self._file = None
        self._seq = 0
        self._queue: List[Tuple[int, str]] = []   # (seq, json line)
        self._in_flight = 0
        self._rotating = False
        # bumped at every rotation start: a drainer batch that raced a
        # rotation (quiesce timeout) detects the epoch change and
        # re-writes itself to the FRESH file instead of silently
        # landing on the replaced inode
        self._rotations = 0
        # the seq fence of the last rotation: entries below it are
        # covered by the snapshot that triggered the rotation and must
        # NOT be re-enqueued (a resurrected pre-snapshot value would
        # regress the key on last-wins replay); entries at/after it
        # are post-export and must SURVIVE the rotation
        self._rotate_cutoff = 0
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self.gate = None
        os.makedirs(directory, exist_ok=True)

    @property
    def path(self) -> str:
        with self._lock:
            return self._path

    def current_seq(self) -> int:
        """The next seq to be assigned — the caller samples it BEFORE a
        state export as the rotation fence: every mutation the export
        can contain was appended (same kv lock) with a smaller seq,
        and anything the export might miss gets a larger one."""
        with self._cond:
            return self._seq

    def append(self, key: str, value: bytes) -> None:
        """Enqueue the RESULTING value of a mutation (b"" = the key was
        deleted); the drainer writes it. Cheap by design: callers hold
        the kv store's condition lock. The payload encoding happens
        OUTSIDE this log's lock (only the seq stamp needs it) so a
        large value never extends the critical section."""
        encoded = base64.b64encode(value).decode("ascii")
        with self._cond:
            if self._stopped:
                return
            seq = self._seq
            self._seq += 1
            line = json.dumps({"seq": seq, "k": key, "v": encoded})
            self._queue.append((seq, line))
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain, daemon=True,
                    name="kv-mutlog-writer")
                self._thread.start()
            self._cond.notify_all()

    def _drain(self) -> None:
        while True:
            with self._cond:
                while self._rotating or (
                        not self._queue and not self._stopped):
                    if self._stopped and not self._queue:
                        return
                    self._cond.wait()
                if self._stopped and not self._queue:
                    return
                batch = self._queue
                self._queue = []
                self._in_flight = len(batch)
                handle = self._file
                epoch = self._rotations
            gate = self.gate
            discarded = False
            try:
                if gate is not None and gate():
                    # fenced: a higher-generation master owns this
                    # lineage — drop instead of corrupting its log
                    discarded = True
                    continue
                # file work OUTSIDE the lock: the handle is drainer-
                # owned between rotations (rotate/close quiesce on
                # _in_flight before touching it), so an append caller
                # holding the kv condition never waits on the disk
                if handle is None:
                    handle = open(self._path, "a")
                    with self._cond:
                        if self._rotations == epoch and \
                                not self._rotating:
                            self._file = handle
                handle.write(
                    "\n".join(line for _, line in batch) + "\n")
                handle.flush()
            except (OSError, ValueError) as e:
                # ValueError: write on a handle rotate closed in the
                # quiesce-timeout corner (the epoch re-check below
                # re-writes the batch to the fresh file)
                logger.warning("mutation log append failed: %s", e)
            except Exception:  # noqa: BLE001 — a broken gate must not
                # kill the writer
                logger.exception("mutation log gate failed")
            finally:
                with self._cond:
                    if not discarded and epoch != self._rotations:
                        # a rotation raced this batch past its quiesce
                        # timeout: the bytes may sit on the replaced
                        # inode. Drop the (possibly stale) handle and
                        # re-enqueue ONLY the post-fence entries for
                        # the fresh file — pre-fence ones are covered
                        # by the snapshot that rotated, and re-writing
                        # them could resurrect a superseded value over
                        # the snapshot's newer one on replay.
                        if handle is not None:
                            try:
                                handle.close()
                            except OSError:
                                pass
                        if self._file is handle:
                            self._file = None
                        keep = [entry for entry in batch
                                if entry[0] >= self._rotate_cutoff]
                        self._queue = keep + self._queue
                    self._in_flight = 0
                    self._cond.notify_all()

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until everything appended so far is on disk (or was
        gate-discarded). Returns False on timeout."""
        import time as time_mod

        deadline = time_mod.time() + timeout_s
        with self._cond:
            while self._queue or self._in_flight:
                remaining = deadline - time_mod.time()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def rotate(self, up_to_seq: Optional[int] = None) -> None:
        """Drop entries the snapshot just made durable. ``up_to_seq``
        is the fence the caller sampled via :meth:`current_seq` BEFORE
        exporting state: entries below it are in (or older than) the
        snapshot and go; entries at/after it may have landed between
        the export and this call — they are in NEITHER the snapshot
        nor (after a naive truncate) the log, so they are preserved in
        the rewritten file and the queue. ``None`` = fence at the
        current seq (drop everything enqueued so far — the caller
        guarantees its snapshot covers the present instant).

        Quiesces the drainer (bounded), then does the file work off
        the lock — ``_rotating`` keeps the drainer from re-opening
        mid-swap."""
        import time as time_mod

        deadline = time_mod.time() + 2.0
        with self._cond:
            fence = self._seq if up_to_seq is None else up_to_seq
            self._queue = [entry for entry in self._queue
                           if entry[0] >= fence]
            while self._in_flight:
                remaining = deadline - time_mod.time()
                if remaining <= 0 or not self._cond.wait(remaining):
                    break     # proceed anyway; the write path survives
            self._rotating = True
            self._rotations += 1
            self._rotate_cutoff = fence
            handle, self._file = self._file, None
        try:
            if handle is not None:
                handle.close()
            # rewrite instead of truncate: drained entries at/after
            # the fence are post-export and must survive the rotation
            survivors = []
            try:
                with open(self._path) as f:
                    for raw in f:
                        raw = raw.strip()
                        if not raw:
                            continue
                        try:
                            if int(json.loads(raw)["seq"]) >= fence:
                                survivors.append(raw)
                        except (ValueError, KeyError, TypeError):
                            continue   # torn line: gone either way
            except OSError:
                pass
            tmp = f"{self._path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                if survivors:
                    f.write("\n".join(survivors) + "\n")
            os.replace(tmp, self._path)
        except OSError as e:
            logger.warning("mutation log rotate failed: %s", e)
        finally:
            with self._cond:
                self._rotating = False
                self._cond.notify_all()

    def close(self) -> None:
        self.flush(timeout_s=2.0)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
            thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
        with self._cond:
            handle, self._file = self._file, None
        if handle is not None:
            try:
                handle.close()
            except OSError:
                pass

    @staticmethod
    def read(directory: str) -> List[Tuple[str, bytes]]:
        """(key, value) pairs in append order, SKIPPING malformed lines
        (a torn tail on crash, or a partial write the writer survived
        and appended past — truncating at the first bad line would
        silently drop every committed mutation after it; skipping is
        safe under the replay's last-wins semantics). Empty when no log
        exists."""
        path = os.path.join(directory, MUTATION_LOG_NAME)
        entries: List[Tuple[str, bytes]] = []
        skipped = 0
        try:
            with open(path) as f:
                lines: Iterable[str] = f.readlines()
        except OSError:
            return entries
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
                entries.append((str(record["k"]),
                                base64.b64decode(record["v"])))
            except (ValueError, KeyError):
                skipped += 1
        if skipped:
            logger.warning(
                "mutation log %s: %d malformed line(s) skipped "
                "(torn/partial writes)", path, skipped)
        return entries


class MasterStateBackend:
    """Versioned snapshot files under one directory.

    Concurrency: one writer (the master process — ``save*`` serializes on
    an internal lock); readers (``load_latest``) tolerate the writer
    replacing files underneath them because replacement is atomic.
    """

    def __init__(self, directory: str, retain: int = 5):
        if retain < 1:
            raise ValueError(f"retain must be >= 1, got {retain}")
        self._dir = directory
        self._retain = retain
        self._lock = threading.Lock()
        # double-primary fencing: wired by the owner (JobMaster sets
        # _check_fenced; a standby pins it permanently closed).  gate()
        # returning True means "deposed": every save becomes a no-op so
        # a stale master cannot interleave snapshot versions with the
        # promoted one's writes over the shared lineage.
        self.gate: Optional[Callable[[], bool]] = None
        os.makedirs(directory, exist_ok=True)
        existing = self.versions()
        self._next_version = (existing[-1] + 1) if existing else 1
        self._last_checksum = ""

    @property
    def directory(self) -> str:
        return self._dir

    def _path(self, version: int) -> str:
        return os.path.join(self._dir, f"master-state-{version:010d}.json")

    def versions(self) -> List[int]:
        """Snapshot versions present on disk, oldest first."""
        found = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return []
        for name in names:
            match = _SNAPSHOT_RE.match(name)
            if match:
                found.append(int(match.group(1)))
        return sorted(found)

    # -- writing -----------------------------------------------------------
    def save(self, state: Dict[str, Any]) -> Optional[str]:
        """Write a new snapshot version atomically; returns its path
        (None when the fence gate reports this writer deposed)."""
        if self.gate is not None and self.gate():
            return None
        payload = _canonical(state)
        return self._write(state, payload)

    def save_if_changed(self, state: Dict[str, Any]) -> Optional[str]:
        """Write only when the state differs from the last written
        snapshot (the per-mutation hook: polls that mutate nothing must
        not churn versions). Returns the path, or None when skipped."""
        if self.gate is not None and self.gate():
            return None
        payload = _canonical(state)
        with self._lock:
            if self._last_checksum and \
                    _checksum(payload) == self._last_checksum:
                return None
        return self._write(state, payload)

    def _write(self, state: Dict[str, Any], payload: str) -> str:
        digest = _checksum(payload)
        with self._lock:
            version = self._next_version
            self._next_version += 1
            path = self._path(version)
            wrapper = {
                "format": _FORMAT_VERSION,
                "version": version,
                "checksum": digest,
                "state": state,
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(wrapper, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._last_checksum = digest
            self._prune()
        obs.get_registry().counter(
            "dlrover_tpu_master_snapshots_total",
            "Control-plane state snapshots written").inc()
        return path

    def _prune(self) -> None:
        """Drop snapshots beyond the retention window (lock held)."""
        versions = self.versions()
        for version in versions[:-self._retain]:
            try:
                os.remove(self._path(version))
            except OSError:
                pass

    # -- reading -----------------------------------------------------------
    def load_version(self, version: int) -> Dict[str, Any]:
        """Load + verify one snapshot; raises SnapshotCorruptionError."""
        path = self._path(version)
        try:
            with open(path) as f:
                wrapper = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SnapshotCorruptionError(
                f"snapshot {path} unreadable: {e}") from e
        state = wrapper.get("state")
        if not isinstance(state, dict):
            raise SnapshotCorruptionError(
                f"snapshot {path} has no state dict")
        if _checksum(_canonical(state)) != wrapper.get("checksum"):
            raise SnapshotCorruptionError(
                f"snapshot {path} failed its checksum")
        return state

    def load_latest(self) -> Optional[Tuple[Dict[str, Any], int]]:
        """Newest valid snapshot as (state, version), walking backwards
        past corrupt ones (each fallback is counted + logged loudly);
        None when no valid snapshot exists."""
        fallbacks = obs.get_registry().counter(
            "dlrover_tpu_master_snapshot_fallbacks_total",
            "Corrupt snapshots skipped during master recovery")
        for version in reversed(self.versions()):
            try:
                return self.load_version(version), version
            except SnapshotCorruptionError as e:
                logger.error(
                    "master state snapshot v%d is corrupt (%s); falling "
                    "back to the previous snapshot", version, e)
                fallbacks.inc()
        return None
