"""Crash-consistent master state: a versioned, checksummed snapshot store.

The job master is the one component whose death previously killed the job:
rendezvous rounds, the node table, dataset task progress and the kv-store
lived only in ``JobMaster``'s memory. This module gives the master durable
control-plane state with the same guarantees a WAL-less embedded store can
offer from atomic-rename filesystems:

- **Atomicity**: every snapshot is written to a temp file in the same
  directory and ``os.replace``d into place — a crash mid-write leaves the
  previous snapshot intact, never a torn file.
- **Integrity**: the snapshot wrapper carries a SHA-256 over the canonical
  JSON of the state payload; ``load_latest`` verifies it and falls back to
  the next-older snapshot on mismatch (torn disk, bit rot, truncation).
- **Bounded retention**: only the newest ``retain`` snapshots are kept, so
  a long job cannot fill the state volume.

The store is deliberately schema-free (one JSON dict per snapshot); the
``JobMaster`` composes the dict from each component's ``export_state()``
and rebuilds them through ``restore_state()`` on restart — see
docs/fault_tolerance.md for the snapshot format and recovery sequence.
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.log import default_logger as logger

_SNAPSHOT_RE = re.compile(r"^master-state-(\d{10})\.json$")
_FORMAT_VERSION = 1
MUTATION_LOG_NAME = "kv-mutlog.jsonl"


def _canonical(state: Dict[str, Any]) -> str:
    """Deterministic JSON for checksumming (and change detection)."""
    return json.dumps(state, sort_keys=True, separators=(",", ":"))


def _checksum(payload: str) -> str:
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


class SnapshotCorruptionError(RuntimeError):
    """A snapshot file failed its checksum / structure validation."""


class MutationLog:
    """Append-only log of the durable-worthy HOT mutations (the
    ``coord/`` barrier keys) between snapshots.

    Snapshots deliberately exclude the gradient-path keys from their
    TRIGGER set (a full export+fsync per training step would put storage
    in the step loop), so the barrier mutations land here instead: one
    JSON line per mutation, buffered writes, NO fsync. ``append`` is an
    in-memory ENQUEUE — a background drainer owns the disk, so the kv
    store's condition lock never waits on the (typically shared/NFS)
    state volume. The log is ROTATED (truncated) every time a snapshot
    is written, because the snapshot's state export includes the hot
    keys' values at that instant: replaying the (strictly newer) log
    over the latest snapshot is therefore always last-wins correct. A
    restarted master — or a promoted hot standby — replays it via
    ``KVStoreService.replay_mutations``.

    ``gate``: an optional callable the DRAINER consults before each
    write; truthy = this master has been fenced (a higher-generation
    master owns the lineage) and the entries are discarded instead of
    written. Checking on the drainer thread means fencing bites even
    when ONLY hot traffic is flowing (nothing else would run the
    fence check), and the check's own file read never runs under the
    kv lock.
    """

    def __init__(self, directory: str):
        self._path = os.path.join(directory, MUTATION_LOG_NAME)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._file = None
        self._seq = 0
        self._queue: List[str] = []
        self._in_flight = 0
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self.gate = None
        os.makedirs(directory, exist_ok=True)

    @property
    def path(self) -> str:
        with self._lock:
            return self._path

    def append(self, key: str, value: bytes) -> None:
        """Enqueue the RESULTING value of a mutation (b"" = the key was
        deleted); the drainer writes it. Cheap by design: callers hold
        the kv store's condition lock."""
        line = json.dumps({
            "seq": self._seq,
            "k": key,
            "v": base64.b64encode(value).decode("ascii"),
        })
        with self._cond:
            if self._stopped:
                return
            self._seq += 1
            self._queue.append(line)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._drain, daemon=True,
                    name="kv-mutlog-writer")
                self._thread.start()
            self._cond.notify_all()

    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._queue:
                    return
                batch = self._queue
                self._queue = []
                self._in_flight = len(batch)
            gate = self.gate
            try:
                if gate is not None and gate():
                    # fenced: a higher-generation master owns this
                    # lineage — drop instead of corrupting its log
                    continue
                with self._lock:
                    if self._file is None:
                        self._file = open(self._path, "a")
                    self._file.write("\n".join(batch) + "\n")
                    self._file.flush()
            except OSError as e:
                logger.warning("mutation log append failed: %s", e)
            except Exception:  # noqa: BLE001 — a broken gate must not
                # kill the writer
                logger.exception("mutation log gate failed")
            finally:
                with self._cond:
                    self._in_flight = 0
                    self._cond.notify_all()

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until everything appended so far is on disk (or was
        gate-discarded). Returns False on timeout."""
        import time as time_mod

        deadline = time_mod.time() + timeout_s
        with self._cond:
            while self._queue or self._in_flight:
                remaining = deadline - time_mod.time()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def rotate(self) -> None:
        """Truncate after a snapshot write: every logged mutation is now
        part of (or older than) the durable snapshot."""
        with self._cond:
            self._queue = []
            try:
                if self._file is not None:
                    self._file.close()
                    self._file = None
                tmp = f"{self._path}.{os.getpid()}.tmp"
                with open(tmp, "w"):
                    pass
                os.replace(tmp, self._path)
            except OSError as e:
                logger.warning("mutation log rotate failed: %s", e)

    def close(self) -> None:
        self.flush(timeout_s=2.0)
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
            thread = self._thread
            if self._file is not None:
                try:
                    self._file.close()
                except OSError:
                    pass
                self._file = None
        if thread is not None:
            thread.join(timeout=2.0)

    @staticmethod
    def read(directory: str) -> List[Tuple[str, bytes]]:
        """(key, value) pairs in append order, SKIPPING malformed lines
        (a torn tail on crash, or a partial write the writer survived
        and appended past — truncating at the first bad line would
        silently drop every committed mutation after it; skipping is
        safe under the replay's last-wins semantics). Empty when no log
        exists."""
        path = os.path.join(directory, MUTATION_LOG_NAME)
        entries: List[Tuple[str, bytes]] = []
        skipped = 0
        try:
            with open(path) as f:
                lines: Iterable[str] = f.readlines()
        except OSError:
            return entries
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
                entries.append((str(record["k"]),
                                base64.b64decode(record["v"])))
            except (ValueError, KeyError):
                skipped += 1
        if skipped:
            logger.warning(
                "mutation log %s: %d malformed line(s) skipped "
                "(torn/partial writes)", path, skipped)
        return entries


class MasterStateBackend:
    """Versioned snapshot files under one directory.

    Concurrency: one writer (the master process — ``save*`` serializes on
    an internal lock); readers (``load_latest``) tolerate the writer
    replacing files underneath them because replacement is atomic.
    """

    def __init__(self, directory: str, retain: int = 5):
        if retain < 1:
            raise ValueError(f"retain must be >= 1, got {retain}")
        self._dir = directory
        self._retain = retain
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        existing = self.versions()
        self._next_version = (existing[-1] + 1) if existing else 1
        self._last_checksum = ""

    @property
    def directory(self) -> str:
        return self._dir

    def _path(self, version: int) -> str:
        return os.path.join(self._dir, f"master-state-{version:010d}.json")

    def versions(self) -> List[int]:
        """Snapshot versions present on disk, oldest first."""
        found = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return []
        for name in names:
            match = _SNAPSHOT_RE.match(name)
            if match:
                found.append(int(match.group(1)))
        return sorted(found)

    # -- writing -----------------------------------------------------------
    def save(self, state: Dict[str, Any]) -> str:
        """Write a new snapshot version atomically; returns its path."""
        payload = _canonical(state)
        return self._write(state, payload)

    def save_if_changed(self, state: Dict[str, Any]) -> Optional[str]:
        """Write only when the state differs from the last written
        snapshot (the per-mutation hook: polls that mutate nothing must
        not churn versions). Returns the path, or None when skipped."""
        payload = _canonical(state)
        with self._lock:
            if self._last_checksum and \
                    _checksum(payload) == self._last_checksum:
                return None
        return self._write(state, payload)

    def _write(self, state: Dict[str, Any], payload: str) -> str:
        digest = _checksum(payload)
        with self._lock:
            version = self._next_version
            self._next_version += 1
            path = self._path(version)
            wrapper = {
                "format": _FORMAT_VERSION,
                "version": version,
                "checksum": digest,
                "state": state,
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(wrapper, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._last_checksum = digest
            self._prune()
        obs.get_registry().counter(
            "dlrover_tpu_master_snapshots_total",
            "Control-plane state snapshots written").inc()
        return path

    def _prune(self) -> None:
        """Drop snapshots beyond the retention window (lock held)."""
        versions = self.versions()
        for version in versions[:-self._retain]:
            try:
                os.remove(self._path(version))
            except OSError:
                pass

    # -- reading -----------------------------------------------------------
    def load_version(self, version: int) -> Dict[str, Any]:
        """Load + verify one snapshot; raises SnapshotCorruptionError."""
        path = self._path(version)
        try:
            with open(path) as f:
                wrapper = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SnapshotCorruptionError(
                f"snapshot {path} unreadable: {e}") from e
        state = wrapper.get("state")
        if not isinstance(state, dict):
            raise SnapshotCorruptionError(
                f"snapshot {path} has no state dict")
        if _checksum(_canonical(state)) != wrapper.get("checksum"):
            raise SnapshotCorruptionError(
                f"snapshot {path} failed its checksum")
        return state

    def load_latest(self) -> Optional[Tuple[Dict[str, Any], int]]:
        """Newest valid snapshot as (state, version), walking backwards
        past corrupt ones (each fallback is counted + logged loudly);
        None when no valid snapshot exists."""
        fallbacks = obs.get_registry().counter(
            "dlrover_tpu_master_snapshot_fallbacks_total",
            "Corrupt snapshots skipped during master recovery")
        for version in reversed(self.versions()):
            try:
                return self.load_version(version), version
            except SnapshotCorruptionError as e:
                logger.error(
                    "master state snapshot v%d is corrupt (%s); falling "
                    "back to the previous snapshot", version, e)
                fallbacks.inc()
        return None
