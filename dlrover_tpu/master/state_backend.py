"""Crash-consistent master state: a versioned, checksummed snapshot store.

The job master is the one component whose death previously killed the job:
rendezvous rounds, the node table, dataset task progress and the kv-store
lived only in ``JobMaster``'s memory. This module gives the master durable
control-plane state with the same guarantees a WAL-less embedded store can
offer from atomic-rename filesystems:

- **Atomicity**: every snapshot is written to a temp file in the same
  directory and ``os.replace``d into place — a crash mid-write leaves the
  previous snapshot intact, never a torn file.
- **Integrity**: the snapshot wrapper carries a SHA-256 over the canonical
  JSON of the state payload; ``load_latest`` verifies it and falls back to
  the next-older snapshot on mismatch (torn disk, bit rot, truncation).
- **Bounded retention**: only the newest ``retain`` snapshots are kept, so
  a long job cannot fill the state volume.

The store is deliberately schema-free (one JSON dict per snapshot); the
``JobMaster`` composes the dict from each component's ``export_state()``
and rebuilds them through ``restore_state()`` on restart — see
docs/fault_tolerance.md for the snapshot format and recovery sequence.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.log import default_logger as logger

_SNAPSHOT_RE = re.compile(r"^master-state-(\d{10})\.json$")
_FORMAT_VERSION = 1


def _canonical(state: Dict[str, Any]) -> str:
    """Deterministic JSON for checksumming (and change detection)."""
    return json.dumps(state, sort_keys=True, separators=(",", ":"))


def _checksum(payload: str) -> str:
    return hashlib.sha256(payload.encode("utf-8")).hexdigest()


class SnapshotCorruptionError(RuntimeError):
    """A snapshot file failed its checksum / structure validation."""


class MasterStateBackend:
    """Versioned snapshot files under one directory.

    Concurrency: one writer (the master process — ``save*`` serializes on
    an internal lock); readers (``load_latest``) tolerate the writer
    replacing files underneath them because replacement is atomic.
    """

    def __init__(self, directory: str, retain: int = 5):
        if retain < 1:
            raise ValueError(f"retain must be >= 1, got {retain}")
        self._dir = directory
        self._retain = retain
        self._lock = threading.Lock()
        os.makedirs(directory, exist_ok=True)
        existing = self.versions()
        self._next_version = (existing[-1] + 1) if existing else 1
        self._last_checksum = ""

    @property
    def directory(self) -> str:
        return self._dir

    def _path(self, version: int) -> str:
        return os.path.join(self._dir, f"master-state-{version:010d}.json")

    def versions(self) -> List[int]:
        """Snapshot versions present on disk, oldest first."""
        found = []
        try:
            names = os.listdir(self._dir)
        except OSError:
            return []
        for name in names:
            match = _SNAPSHOT_RE.match(name)
            if match:
                found.append(int(match.group(1)))
        return sorted(found)

    # -- writing -----------------------------------------------------------
    def save(self, state: Dict[str, Any]) -> str:
        """Write a new snapshot version atomically; returns its path."""
        payload = _canonical(state)
        return self._write(state, payload)

    def save_if_changed(self, state: Dict[str, Any]) -> Optional[str]:
        """Write only when the state differs from the last written
        snapshot (the per-mutation hook: polls that mutate nothing must
        not churn versions). Returns the path, or None when skipped."""
        payload = _canonical(state)
        with self._lock:
            if self._last_checksum and \
                    _checksum(payload) == self._last_checksum:
                return None
        return self._write(state, payload)

    def _write(self, state: Dict[str, Any], payload: str) -> str:
        digest = _checksum(payload)
        with self._lock:
            version = self._next_version
            self._next_version += 1
            path = self._path(version)
            wrapper = {
                "format": _FORMAT_VERSION,
                "version": version,
                "checksum": digest,
                "state": state,
            }
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(wrapper, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._last_checksum = digest
            self._prune()
        obs.get_registry().counter(
            "dlrover_tpu_master_snapshots_total",
            "Control-plane state snapshots written").inc()
        return path

    def _prune(self) -> None:
        """Drop snapshots beyond the retention window (lock held)."""
        versions = self.versions()
        for version in versions[:-self._retain]:
            try:
                os.remove(self._path(version))
            except OSError:
                pass

    # -- reading -----------------------------------------------------------
    def load_version(self, version: int) -> Dict[str, Any]:
        """Load + verify one snapshot; raises SnapshotCorruptionError."""
        path = self._path(version)
        try:
            with open(path) as f:
                wrapper = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise SnapshotCorruptionError(
                f"snapshot {path} unreadable: {e}") from e
        state = wrapper.get("state")
        if not isinstance(state, dict):
            raise SnapshotCorruptionError(
                f"snapshot {path} has no state dict")
        if _checksum(_canonical(state)) != wrapper.get("checksum"):
            raise SnapshotCorruptionError(
                f"snapshot {path} failed its checksum")
        return state

    def load_latest(self) -> Optional[Tuple[Dict[str, Any], int]]:
        """Newest valid snapshot as (state, version), walking backwards
        past corrupt ones (each fallback is counted + logged loudly);
        None when no valid snapshot exists."""
        fallbacks = obs.get_registry().counter(
            "dlrover_tpu_master_snapshot_fallbacks_total",
            "Corrupt snapshots skipped during master recovery")
        for version in reversed(self.versions()):
            try:
                return self.load_version(version), version
            except SnapshotCorruptionError as e:
                logger.error(
                    "master state snapshot v%d is corrupt (%s); falling "
                    "back to the previous snapshot", version, e)
                fallbacks.inc()
        return None
