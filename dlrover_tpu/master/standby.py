"""Hot-standby master: tail the snapshot stream, health-check the
primary, promote without a reconnect storm.

PR 3 made a master RESTART survivable (crash-consistent snapshots +
agent reconnection), but recovery still waited for someone to start a
new master process and for that process to read state cold. The standby
closes the gap:

- **Warm state**: the standby tails the primary's snapshot stream (the
  shared ``--state-dir``) and keeps the newest valid snapshot parsed in
  memory; the hot keys snapshots deliberately exclude from their trigger
  set ride the mutation log (state_backend.MutationLog), which promotion
  replays on top.
- **Health checks**: the primary's advertised address is read from the
  bootstrap file it publishes; a cheap ``JobStatusRequest`` probes it on
  ``Context.standby_health_interval_s``. ``standby_promote_failures``
  CONSECUTIVE failed probes — not one blip — trigger promotion.
- **Promotion without a storm**: the standby constructs a full
  ``JobMaster`` from its warm state (generation = snapshot generation +
  1) and atomically rewrites the bootstrap file. Agents already in
  master-lost mode re-resolve from that file and re-register through the
  EXISTING reconnect handshake; the restored rendezvous state answers
  ``world_intact=True``, so workers never stop and nobody re-joins
  rendezvous — PR 3's master-lost mode becomes a bounded blip, and the
  PR 8 slice-absent budget stops ticking the moment slice status serves
  again.
- **Fencing**: the bootstrap file carries a generation token and
  ``JobMaster._publish_bootstrap_addr`` refuses to overwrite a higher
  one — a revived old primary cannot steal the fleet back
  (double-primary split brain; see docs/fault_tolerance.md).

CLI: ``python -m dlrover_tpu.master.job_master --standby --state-dir ...
--bootstrap-file ...`` (run_master_main), or embed via
``StandbyMaster(...).start()``.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.state_backend import MasterStateBackend


class StandbyMaster:
    """One hot standby for one job's master. Watches, warms, promotes."""

    def __init__(self, state_dir: str,
                 bootstrap_file: Optional[str] = None,
                 port: int = 0, host: str = "0.0.0.0",
                 min_nodes: int = 1, max_nodes: int = 1,
                 node_unit: int = 1,
                 health_interval_s: Optional[float] = None,
                 promote_failures: Optional[int] = None):
        if not state_dir:
            raise ValueError("a standby needs the primary's --state-dir "
                             "(the snapshot stream it tails)")
        ctx = Context.singleton()
        if bootstrap_file:
            ctx.update(master_bootstrap_file=bootstrap_file)
        if not ctx.master_bootstrap_file:
            raise ValueError(
                "a standby needs the bootstrap file the primary "
                "publishes (--bootstrap-file): it is both the health-"
                "check target and the promotion handoff")
        self._state_dir = state_dir
        self._port = port
        self._host = host
        self._min_nodes = min_nodes
        self._max_nodes = max_nodes
        self._node_unit = node_unit
        self._health_interval_s = (
            health_interval_s if health_interval_s is not None
            else ctx.standby_health_interval_s)
        self._promote_failures = max(1, (
            promote_failures if promote_failures is not None
            else ctx.standby_promote_failures))
        self._backend = MasterStateBackend(state_dir)
        # a standby must never write the snapshot lineage it tails —
        # the backend stays permanently fenced (promotion hands the
        # state dir to a fresh JobMaster with its own gated backend)
        self._backend.gate = lambda: True
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # guards the watch-state shared between the standby thread
        # (run/refresh/probe) and the caller thread (start/stop/tests);
        # never held across a probe RPC or a snapshot disk read
        self._lock = threading.Lock()
        # warm state: (state dict, snapshot version) — what promotion
        # hands to JobMaster so it skips the cold disk read
        self.warm_state: Optional[Tuple[dict, int]] = None
        self.warm_version = -1
        self.consecutive_failures = 0
        self.promoted_master = None
        self._probe_client = None
        self._probe_addr = ""

    # -- snapshot tailing -------------------------------------------------
    def refresh_warm_state(self) -> bool:
        """Load the newest snapshot if the stream advanced past what we
        hold; returns whether anything new was adopted."""
        with self._lock:
            held_version = self.warm_version
        versions = self._backend.versions()
        if not versions or versions[-1] <= held_version:
            return False
        loaded = self._backend.load_latest()
        if loaded is None:
            return False
        state, version = loaded
        with self._lock:
            if version <= self.warm_version:
                return False
            self.warm_state = (state, version)
            self.warm_version = version
        obs.get_registry().gauge(
            "dlrover_tpu_standby_warm_snapshot_version",
            "Newest snapshot version the hot standby holds parsed in "
            "memory").set(version)
        return True

    # -- health checking --------------------------------------------------
    def _primary_addr(self) -> str:
        from dlrover_tpu.agent.master_client import MasterClient

        return MasterClient.resolve_bootstrap().get("addr", "")

    def check_primary(self) -> bool:
        """One probe: resolve the primary from the bootstrap file and
        round-trip a JobStatusRequest with a short deadline. No
        published primary yet = healthy (nothing to take over)."""
        from dlrover_tpu.agent.master_client import MasterClient

        addr = self._primary_addr()
        if not addr:
            return True
        with self._lock:
            probe = self._probe_client
            stale = None
            if addr != self._probe_addr or probe is None:
                # channel construction is lazy (no connect): safe to
                # swap under the lock; the dead channel closes outside
                stale, probe = probe, MasterClient(
                    addr, node_id=-1, node_type="standby",
                    timeout_s=max(1.0, self._health_interval_s))
                self._probe_client = probe
                self._probe_addr = addr
        if stale is not None:
            try:
                stale.close()
            except Exception:  # noqa: BLE001 — dead channel
                pass
        try:
            probe.get_job_status()
            return True
        except Exception:  # noqa: BLE001 — any failure is a failed probe
            return False

    # -- the watch loop ---------------------------------------------------
    def run(self) -> int:
        """Watch until promotion (then serve as the master: returns its
        exit code) or stop() (returns 0)."""
        logger.info(
            "hot standby watching %s (probe every %.1fs, promote after "
            "%d consecutive failures)", self._state_dir,
            self._health_interval_s, self._promote_failures)
        obs.get_flight_recorder().record_event(
            "standby_started", state_dir=self._state_dir,
            health_interval_s=self._health_interval_s,
            promote_failures=self._promote_failures)
        while not self._stopped.is_set():
            self.refresh_warm_state()
            if self.check_primary():
                with self._lock:
                    self.consecutive_failures = 0
            else:
                with self._lock:
                    self.consecutive_failures += 1
                    failures = self.consecutive_failures
                logger.warning(
                    "primary health probe failed (%d/%d consecutive)",
                    failures, self._promote_failures)
                if failures >= self._promote_failures:
                    master = self.promote()
                    if master is not None:
                        return master.run()
            self._stopped.wait(self._health_interval_s)
        return 0

    def start(self) -> threading.Thread:
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="standby-master")
        self._thread.start()
        return self._thread

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            promoted = self.promoted_master
            probe = self._probe_client
        if promoted is not None:
            promoted.stop(grace_s=0.1)
        if probe is not None:
            try:
                probe.close()
            except Exception:  # noqa: BLE001
                pass

    # -- promotion --------------------------------------------------------
    def promote(self):
        """Become the primary: a full JobMaster from the warm state
        (generation = snapshot's + 1, mutation log replayed), serving
        immediately, bootstrap file atomically rewritten. Agents'
        reconnect handshakes find their worlds intact in the restored
        rendezvous state — zero worker restarts, zero re-register
        storm."""
        from dlrover_tpu.master.job_master import JobMaster

        started = time.monotonic()
        # one last look at the stream: the primary may have snapshotted
        # between our last tail and its death
        self.refresh_warm_state()
        with self._lock:
            warm_state = self.warm_state
            warm_version = self.warm_version
            failures = self.consecutive_failures
        logger.critical(
            "PROMOTING: primary failed %d consecutive health probes; "
            "standby takes over from snapshot v%d",
            failures, warm_version)
        master = JobMaster(
            port=self._port, min_nodes=self._min_nodes,
            max_nodes=self._max_nodes, node_unit=self._node_unit,
            host=self._host, state_dir=self._state_dir,
            preloaded_state=warm_state)
        master.prepare()   # serves + publishes the bootstrap handoff
        took_s = time.monotonic() - started
        with self._lock:
            self.promoted_master = master
        obs.get_flight_recorder().record_event(
            "master_promoted", addr=master.addr,
            coord_addr=master.coord_addr,
            generation=master.generation,
            snapshot_version=warm_version,
            failed_probes=failures,
            promotion_s=round(took_s, 4))
        obs.get_registry().counter(
            "dlrover_tpu_master_promotions_total",
            "Hot-standby masters promoted to primary").inc()
        obs.record_span("master_promotion", took_s,
                        attrs={"generation": master.generation,
                               "snapshot_version": warm_version})
        logger.critical(
            "PROMOTED in %.3fs: serving at %s (coord %s) as generation "
            "%d", took_s, master.addr, master.coord_addr or "-",
            master.generation)
        return master
