"""DiagnosisManager: run the inference chain, persist reports, dispatch
actions.

The master-side consumer of everything PR 2's telemetry plumbing
collects: on a fixed cadence (``Context.diagnosis_interval_s``) it
snapshots the SpeedMonitor's per-worker step reports and the latest
NodeResourceStats, runs the rule chain (rules.py), and for every
conclusion

- appends a :class:`DiagnosisReport` to a bounded ring (exported through
  the PR 3 state backend so a restarted master keeps its history),
- records a ``diagnosis`` flight event + bumps
  ``dlrover_tpu_diagnosis_reports_total{rule,severity}``,
- enqueues the report's actions onto per-rank queues agents drain via
  the polled ``DiagnosisActionRequest`` RPC (kill-switch:
  ``Context.diagnosis_actions_enabled``; per-rank cooldown so a
  persistently slow rank is profiled once, not every interval).

Threading: fed from servicer threads (``observe_resource_stats``,
``poll_actions``) and read by scrapes while the diagnose loop runs —
every shared structure is guarded by ``self._lock``. Rule evaluation is
serialized under ``self._diag_lock`` (rule hysteresis state is lock-free
by contract); ``_diag_lock`` may take ``self._lock`` inside it, never
the reverse.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.diagnosis.rules import (
    DiagnosisReport,
    DiagnosisSnapshot,
    Rule,
    default_rules,
    parse_action,
    straggler_scores,
)

_REPORT_RING = 256        # reports retained in memory
_PERSISTED_REPORTS = 64   # newest reports carried in state snapshots
_ACTION_QUEUE_CAP = 8     # per-rank pending actions (drop-oldest)
# resource stats older than this are not evidence (the node stopped
# reporting — its last sample describes a process that may be gone)
_STATS_FRESH_S = 120.0


class DiagnosisManager:
    def __init__(self, speed_monitor, rules: Optional[List[Rule]] = None,
                 goodput_ledger=None, plan_calibration=None,
                 steptrace=None):
        self._speed_monitor = speed_monitor
        self._rules = rules if rules is not None else default_rules()
        # optional goodput ledger (obs/goodput.py): its trailing-window
        # summary rides on every snapshot as the GoodputRule's evidence
        self._goodput_ledger = goodput_ledger
        # optional planner calibration (parallel/calibration.py): the
        # running plan's predicted-vs-measured entry is the
        # PlanRegressionRule's evidence
        self._plan_calibration = plan_calibration
        # optional steptrace assembler (master/steptrace.py): its
        # windowed critical-path summary is the CriticalPathRule's
        # evidence
        self._steptrace = steptrace
        self._lock = threading.Lock()
        self._diag_lock = threading.Lock()
        self._reports: deque = deque(maxlen=_REPORT_RING)
        # graftlint: ephemeral(evidence; re-accumulates from the next resource reports)
        self._node_stats: Dict[int, Dict[str, Any]] = {}
        self._pending: Dict[int, deque] = {}
        self._last_action_ts: Dict[int, float] = {}
        self._next_action_id = 1
        # graftlint: ephemeral(published-gauge dedup; republished on the next round)
        self._published_scores: set = set()
        self._stopped = threading.Event()
        # graftlint: ephemeral(loop thread handle; start() spawns a fresh one)
        self._thread: Optional[threading.Thread] = None
        # crash-consistency hook (JobMaster wires _maybe_snapshot): new
        # reports should survive a master restart
        self.state_sink: Optional[callable] = None
        # calibration feedback hook (JobMaster wires the servicer's
        # push_axis_discounts): the learned discounts are recomputed on
        # THIS loop's cadence, not per step report — the medians only
        # move as samples accumulate, and the per-report path must stay
        # appends-only
        self.discount_sink: Optional[callable] = None
        registry = obs.get_registry()
        self._reports_total = registry.counter(
            "dlrover_tpu_diagnosis_reports_total",
            "Diagnosis reports emitted by the inference chain",
            labelnames=("rule", "severity"))
        self._actions_total = registry.counter(
            "dlrover_tpu_diagnosis_actions_total",
            "Diagnosis actions dispatched to agent queues",
            labelnames=("kind",))
        # per-worker gauges carry the rank's slice (multi-slice
        # hierarchical DP; "-1" on single-slice jobs) so dashboards can
        # group by failure domain and a departing SLICE evicts as a unit
        # graftlint: ephemeral(re-pushed at JobMaster._restore_state)
        self._slice_map: Dict[int, int] = {}
        self._score_gauge = registry.gauge(
            "dlrover_tpu_worker_straggler_score",
            "Worker mean step time over the fleet median (1.0 = at the "
            "pack)", labelnames=("node", "slice"))
        self._wait_gauge = registry.gauge(
            "dlrover_tpu_worker_data_wait_fraction",
            "Windowed fraction of worker step time spent waiting on "
            "data", labelnames=("node", "slice"))
        self._mfu_gauge = registry.gauge(
            "dlrover_tpu_worker_mfu",
            "Windowed per-rank achieved model-FLOPs utilization (from "
            "step reports; absent without a FLOPs model)",
            labelnames=("node", "slice"))
        self._hbm_peak_gauge = registry.gauge(
            "dlrover_tpu_worker_hbm_peak_mb",
            "Per-rank device-truth HBM peak watermark over the last "
            "report window (in-step transient, obs/device.py; absent "
            "on backends with no memory stats)",
            labelnames=("node", "slice"))

    # -- slice membership (multi-slice hierarchical DP) --------------------
    def set_slice_map(self, slice_map: Dict[int, int]) -> None:
        """rank → slice from the rendezvous slice registry (servicer
        pushes on every slice-carrying join)."""
        with self._lock:
            self._slice_map = dict(slice_map)

    def _slice_label(self, rank: int) -> str:
        with self._lock:
            return str(self._slice_map.get(rank, -1))

    # -- evidence feeds (servicer threads) ---------------------------------
    def observe_resource_stats(self, stats: msg.NodeResourceStats) -> None:
        # keyed by RANK when the sender provides one: every other piece
        # of diagnosis evidence (step reports, action queues, eviction
        # sets) is rank-keyed, and node_id diverges from rank after a
        # relaunch — a node_id key here would dodge eviction and make
        # HBM reports name a different identity space than straggler
        # reports
        rank = stats.node_rank if stats.node_rank >= 0 else stats.node_id
        entry = {
            "ts": time.time(),
            "cpu_percent": stats.cpu_percent,
            "memory_mb": stats.memory_mb,
            "chips": [{"index": c.index,
                       "duty_cycle_pct": c.duty_cycle_pct,
                       "hbm_used_mb": c.hbm_used_mb,
                       "hbm_total_mb": c.hbm_total_mb,
                       "hbm_peak_mb": getattr(c, "hbm_peak_mb", -1.0)}
                      for c in stats.chip_stats],
        }
        with self._lock:
            # a fresher step-report watermark must survive the slower
            # chip-stats relay overwriting the entry — but it carries
            # its OWN age: a wedged loop (no step reports) keeps the
            # chip relay alive, and its last watermark must expire
            # with the window it described, not ride the relay's ts
            previous = self._node_stats.get(rank)
            if previous and previous.get("hbm_peak_mb", -1.0) >= 0.0:
                peak_ts = float(previous.get("hbm_peak_ts", 0.0))
                if time.time() - peak_ts <= _STATS_FRESH_S:
                    entry["hbm_peak_mb"] = previous["hbm_peak_mb"]
                    entry["hbm_peak_ts"] = peak_ts
            self._node_stats[rank] = entry

    def observe_step_watermark(self, rank: int, peak_mb: float) -> None:
        """Device-truth HBM peak watermark from a step report
        (GlobalStepReport.hbm_peak_bytes → servicer): report-interval
        cadence, the in-step transient — HbmPressureRule's preferred
        signal over the between-steps chip-stats sample."""
        if peak_mb < 0.0:
            return
        with self._lock:
            entry = self._node_stats.get(rank)
            if entry is None:
                entry = {"ts": time.time(), "chips": []}
                self._node_stats[rank] = entry
            entry["hbm_peak_mb"] = float(peak_mb)
            entry["hbm_peak_ts"] = time.time()
            entry["ts"] = time.time()

    def observe_worker_exit(self, rank: int, exit_kind: str,
                            detail: str = "") -> None:
        """A worker departed: record HOW (the diagnosis layer must tell
        hang from crash from drain — they demand different operator
        responses and different relaunch arithmetic)."""
        from dlrover_tpu.common.constants import NodeExitReason

        severity = {
            NodeExitReason.DRAINED: "info",
            NodeExitReason.SUCCEEDED: "info",
            NodeExitReason.HANG: "warning",
        }.get(exit_kind, "warning")
        report = DiagnosisReport(
            rule="worker_exit", severity=severity, worker_id=rank,
            summary=(f"worker {rank} exited: {exit_kind}"
                     + (f" ({detail})" if detail else "")),
            details={"exit_kind": exit_kind},
            ts=time.time(),
        )
        with self._diag_lock:
            self._emit(report, Context.singleton())

    def observe_drain_notice(self, rank: int, deadline: float,
                             reason: str = "",
                             slice_id: int = -1) -> None:
        """A preemption notice arrived for ``rank``: record the planned
        departure so postmortems show the drain was ADVANCE-notified
        (and, in slice mode, which slice drains as a unit)."""
        scope = (f"slice {slice_id} drains as a unit"
                 if slice_id >= 0 else "")
        report = DiagnosisReport(
            rule="preemption", severity="info", worker_id=rank,
            summary=(f"worker {rank} draining: departs in "
                     f"{max(0.0, deadline - time.time()):.0f}s"
                     + (f" ({reason})" if reason else "")
                     + (f" [{scope}]" if scope else "")),
            details={"deadline": deadline, "reason": reason,
                     "slice": slice_id},
            ts=time.time(),
        )
        with self._diag_lock:
            self._emit(report, Context.singleton())

    def observe_autoscale(self, kind: str, reason: str,
                          evidence: Optional[Dict[str, Any]] = None,
                          severity: str = "info") -> None:
        """A fleet-controller decision (brain/fleet_controller.py):
        claim / shed / hold / rollback lands in the report history so
        postmortems read WHY the fleet changed shape next to the
        straggler and goodput evidence that drove it."""
        report = DiagnosisReport(
            rule="autoscale", severity=severity, worker_id=-1,
            summary=f"autoscale {kind}: {reason}",
            details=dict(evidence or {}, kind=kind),
            ts=time.time(),
        )
        with self._diag_lock:
            self._emit(report, Context.singleton())

    def request_checkpoint(self, ranks, deadline: float,
                           reason: str = "") -> List[int]:
        """Urgent ``checkpoint`` fan-out (a peer is draining): enqueue a
        save-now action for every given rank, BYPASSING the per-rank
        cooldown — preemption does not wait for cooldowns. Returns the
        ranks actually queued. The ``diagnosis_actions_enabled``
        kill-switch still applies: diagnose-only means NO agent-side
        effects, urgent or not."""
        return self._request_urgent("checkpoint", ranks, deadline,
                                    reason)

    def request_drain(self, ranks, deadline: float,
                      reason: str = "") -> List[int]:
        """Slice-unit drain fan-out: save-and-EXIT actions for the
        same-slice peers of a rank that received a preemption notice
        (the whole slice departs together; its world dies with it
        either way). Same urgency contract as request_checkpoint."""
        return self._request_urgent("drain", ranks, deadline, reason)

    def _request_urgent(self, kind: str, ranks, deadline: float,
                        reason: str = "") -> List[int]:
        if not Context.singleton().diagnosis_actions_enabled:
            logger.warning(
                "diagnosis actions disabled: urgent %s fan-out "
                "for draining peer suppressed (ranks %s)", kind,
                list(ranks))
            return []
        queued: List[int] = []
        now = time.time()
        with self._lock:
            for rank in ranks:
                queue = self._pending.get(rank)
                if queue is None:
                    queue = deque(maxlen=_ACTION_QUEUE_CAP)
                    self._pending[rank] = queue
                action_id = self._next_action_id
                self._next_action_id += 1
                queue.append({
                    "id": action_id,
                    "kind": kind,
                    "rank": rank,
                    "rule": "preemption",
                    "reason": reason,
                    "deadline": deadline,
                    "ts": now,
                })
                queued.append(rank)
        for rank in queued:
            self._actions_total.labels(kind=kind).inc()
            obs.get_flight_recorder().record_event(
                "diagnosis_action", kind=kind, rank=rank,
                rule="preemption")
        return queued

    def evict_workers(self, live) -> None:
        """Membership-change hook: a departed rank's queued actions and
        cached stats must not outlive it (an agent re-joining under the
        same rank would execute a dead world's restart)."""
        live_set = set(live)
        with self._lock:
            for table in (self._node_stats, self._pending,
                          self._last_action_ts):
                for rank in list(table):
                    if rank not in live_set:
                        table.pop(rank, None)

    # -- the chain ---------------------------------------------------------
    def snapshot(self) -> DiagnosisSnapshot:
        now = time.time()
        with self._lock:
            stats = {rank: entry
                     for rank, entry in self._node_stats.items()
                     if now - entry["ts"] <= _STATS_FRESH_S}
        goodput = None
        if self._goodput_ledger is not None:
            try:
                goodput = self._goodput_ledger.window_summary(
                    Context.singleton().goodput_window_s)
            except Exception:  # noqa: BLE001 — evidence, not the chain
                logger.exception("goodput window summary failed")
        calibration = None
        if self._plan_calibration is not None:
            try:
                calibration = self._plan_calibration.current()
            except Exception:  # noqa: BLE001 — evidence, not the chain
                logger.exception("plan calibration read failed")
        steptrace = None
        if self._steptrace is not None:
            try:
                steptrace = self._steptrace.summary()
            except Exception:  # noqa: BLE001 — evidence, not the chain
                logger.exception("steptrace summary read failed")
        return DiagnosisSnapshot(
            ts=now,
            worker_speeds=self._speed_monitor.worker_speeds(),
            running_speed=self._speed_monitor.running_speed(),
            peak_speed=self._speed_monitor.peak_speed(),
            running_workers=self._speed_monitor.num_running_workers,
            node_stats=stats,
            running_mfu=self._speed_monitor.running_mfu(),
            peak_mfu=self._speed_monitor.peak_mfu(),
            goodput=goodput,
            plan_calibration=calibration,
            steptrace=steptrace,
        )

    def diagnose_once(self) -> List[DiagnosisReport]:
        """One evaluation of the whole chain; safe to call from tests or
        an operator path while the loop runs (serialized)."""
        ctx = Context.singleton()
        with self._diag_lock:
            snap = self.snapshot()
            self._publish_worker_gauges(snap, ctx)
            reports: List[DiagnosisReport] = []
            for rule in self._rules:
                try:
                    reports.extend(rule.evaluate(snap, ctx))
                except Exception:  # noqa: BLE001 — one rule, not the chain
                    logger.exception("diagnosis rule %s failed", rule.name)
            for report in reports:
                report.ts = report.ts or snap.ts
                self._emit(report, ctx)
        if reports and self.state_sink is not None:
            try:
                self.state_sink()
            except Exception:  # noqa: BLE001 — durability is best-effort
                logger.exception("diagnosis state snapshot failed")
        if self._plan_calibration is not None \
                and self.discount_sink is not None:
            try:
                self.discount_sink(
                    self._plan_calibration.axis_discounts())
            except Exception:  # noqa: BLE001 — advisory feedback
                logger.exception("axis discount push failed")
        return reports

    def _publish_worker_gauges(self, snap: DiagnosisSnapshot,
                               ctx: Context) -> None:
        scores = straggler_scores(snap.worker_speeds,
                                  ctx.diagnosis_min_worker_samples)
        # published keys are (node, slice) label pairs: whole-slice
        # eviction on slice departure falls out of the set difference —
        # every member's pair goes stale together
        published = set()

        def _key(rank: int):
            return str(rank), self._slice_label(rank)

        for rank, score in scores.items():
            node, slice_ = _key(rank)
            self._score_gauge.labels(node=node, slice=slice_).set(score)
            published.add((node, slice_))
        for rank, speed in snap.worker_speeds.items():
            node, slice_ = _key(rank)
            if speed.data_wait_fraction >= 0.0:
                self._wait_gauge.labels(node=node, slice=slice_).set(
                    speed.data_wait_fraction)
                published.add((node, slice_))
            if speed.mfu >= 0.0:
                self._mfu_gauge.labels(node=node, slice=slice_).set(
                    speed.mfu)
                published.add((node, slice_))
        for rank, stats in snap.node_stats.items():
            peak = float(stats.get("hbm_peak_mb", -1.0) or -1.0)
            if peak >= 0.0:
                node, slice_ = _key(rank)
                self._hbm_peak_gauge.labels(node=node,
                                            slice=slice_).set(peak)
                published.add((node, slice_))
        with self._lock:
            stale = self._published_scores - published
            self._published_scores = published
        for node, slice_ in stale:
            # dead ranks must not keep ranking in scrapes
            self._score_gauge.remove(node=node, slice=slice_)
            self._wait_gauge.remove(node=node, slice=slice_)
            self._mfu_gauge.remove(node=node, slice=slice_)
            self._hbm_peak_gauge.remove(node=node, slice=slice_)

    def _emit(self, report: DiagnosisReport, ctx: Context) -> None:
        record = report.to_dict()
        with self._lock:
            self._reports.append(record)
        self._reports_total.labels(rule=report.rule,
                                   severity=report.severity).inc()
        obs.get_flight_recorder().record_event(
            "diagnosis", rule=report.rule, severity=report.severity,
            worker=report.worker_id, summary=report.summary,
            actions=list(report.actions))
        logger.log(
            30 if report.severity != "info" else 20,
            "diagnosis [%s/%s]: %s", report.rule, report.severity,
            report.summary)
        if not ctx.diagnosis_actions_enabled:
            return
        for action in report.actions:
            self._enqueue_action(action, report, ctx)

    def _enqueue_action(self, action: str, report: DiagnosisReport,
                        ctx: Context) -> None:
        parsed = parse_action(action)
        kind, rank = parsed["kind"], parsed["rank"]
        if kind in ("observe", "alert") or rank < 0:
            # advisory kinds surface through the report itself; only
            # targeted kinds travel to an agent
            return
        now = time.time()
        with self._lock:
            last = self._last_action_ts.get(rank, 0.0)
            if now - last < ctx.diagnosis_action_cooldown_s:
                return
            self._last_action_ts[rank] = now
            queue = self._pending.get(rank)
            if queue is None:
                queue = deque(maxlen=_ACTION_QUEUE_CAP)
                self._pending[rank] = queue
            action_id = self._next_action_id
            self._next_action_id += 1
            entry = {
                "id": action_id,
                "kind": kind,
                "rank": rank,
                "rule": report.rule,
                "reason": report.summary,
                "ts": now,
            }
            if kind == "profile":
                entry["num_steps"] = ctx.diagnosis_profile_steps
            queue.append(entry)
        self._actions_total.labels(kind=kind).inc()
        obs.get_flight_recorder().record_event(
            "diagnosis_action", kind=kind, rank=rank, id=entry["id"],
            rule=report.rule)

    # -- agent / tools endpoints (servicer threads) ------------------------
    def poll_actions(self, node_rank: int) -> List[Dict[str, Any]]:
        """Pop (single-delivery) every action queued for this rank."""
        with self._lock:
            queue = self._pending.get(node_rank)
            if not queue:
                return []
            actions = list(queue)
            queue.clear()
            return actions

    def reports(self, limit: int = 0) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self._reports)
        if limit > 0:
            records = records[-limit:]
        return records

    def pending_action_counts(self) -> Dict[int, int]:
        with self._lock:
            return {rank: len(queue)
                    for rank, queue in self._pending.items() if queue}

    # -- loop --------------------------------------------------------------
    def start(self, interval_s: Optional[float] = None) -> None:
        interval = (interval_s if interval_s is not None
                    else Context.singleton().diagnosis_interval_s)

        def _loop():
            while not self._stopped.wait(interval):
                try:
                    self.diagnose_once()
                except Exception:  # noqa: BLE001 — loop must survive
                    logger.exception("diagnosis round failed")

        with self._lock:
            if self._thread is not None:
                return
            self._stopped.clear()
            thread = threading.Thread(target=_loop, daemon=True,
                                      name="diagnosis-manager")
            self._thread = thread
        thread.start()

    def stop(self) -> None:
        self._stopped.set()
        with self._lock:
            self._thread = None

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        with self._lock:
            return {
                "reports": list(self._reports)[-_PERSISTED_REPORTS:],
                "next_action_id": self._next_action_id,
            }

    def restore_state(self, state: dict) -> None:
        """Rehydrate report history + the action-id sequence. Pending
        action queues and rule hysteresis deliberately restart empty:
        they describe a world the restarted master has not re-observed
        yet (agents re-register; evidence re-accumulates in one
        window)."""
        reports = state.get("reports", [])
        with self._lock:
            self._reports.clear()
            for record in reports:
                if isinstance(record, dict):
                    self._reports.append(record)
            self._next_action_id = max(
                1, int(state.get("next_action_id", 1)))
            self._pending.clear()
            self._last_action_ts.clear()

    # -- wire helpers ------------------------------------------------------
    @staticmethod
    def actions_to_json(actions: List[Dict[str, Any]]) -> str:
        return json.dumps(actions) if actions else ""

    @staticmethod
    def reports_to_json(reports: List[Dict[str, Any]]) -> str:
        return json.dumps(reports) if reports else ""
