"""Training diagnosis engine: the layer where telemetry becomes
decisions (docs/observability.md, "Diagnosis")."""

from dlrover_tpu.master.diagnosis.manager import DiagnosisManager
from dlrover_tpu.master.diagnosis.rules import (
    DataPipelineBoundRule,
    DiagnosisReport,
    DiagnosisSnapshot,
    GoodputRule,
    HbmPressureRule,
    StragglerRule,
    ThroughputCollapseRule,
    default_rules,
    parse_action,
    straggler_scores,
)

__all__ = [
    "DataPipelineBoundRule",
    "DiagnosisManager",
    "DiagnosisReport",
    "DiagnosisSnapshot",
    "GoodputRule",
    "HbmPressureRule",
    "StragglerRule",
    "ThroughputCollapseRule",
    "default_rules",
    "parse_action",
    "straggler_scores",
]
