"""Diagnosis rules: the inference chain over job telemetry.

Capability parity: dlrover/python/master/diagnosis — the reference runs
an "inference chain" turning raw observations (worker speed, resource
stats, heartbeats) into conclusions and actions. Re-design: each rule is
a small stateful object evaluated over one immutable
:class:`DiagnosisSnapshot`; a conclusion is a :class:`DiagnosisReport`
carrying zero or more actions in the grammar
``observe | profile:{rank} | restart:{rank} | alert``.

Rule state (straggler hysteresis counters) is mutated ONLY inside
``evaluate`` — the :class:`~dlrover_tpu.master.diagnosis.manager.
DiagnosisManager` serializes evaluations under its own lock, so rules
themselves stay lock-free.
"""

from __future__ import annotations

import dataclasses
import statistics
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.config import Context
from dlrover_tpu.master.speed_monitor import WorkerSpeed

# severity levels, mildest first
INFO = "info"
WARNING = "warning"
CRITICAL = "critical"

# action grammar kinds (docs/observability.md)
ACTION_OBSERVE = "observe"
ACTION_PROFILE = "profile"
ACTION_RESTART = "restart"
# urgent save-now-keep-running: fanned out to survivors when a peer
# announces a preemption drain (the agent writes the worker's drain
# request file with exit=False)
ACTION_CHECKPOINT = "checkpoint"
# save-and-EXIT: fanned out to the SAME-SLICE peers of a draining rank
# (the slice drains as a unit — its jax world dies with the slice; the
# agent writes the drain request with exit=True and departs cleanly)
ACTION_DRAIN = "drain"
ACTION_ALERT = "alert"


@dataclasses.dataclass
class DiagnosisSnapshot:
    """One immutable view of the evidence a diagnosis round runs over."""

    ts: float
    worker_speeds: Dict[int, WorkerSpeed]
    running_speed: float = 0.0
    peak_speed: float = 0.0
    running_workers: int = 0
    # worker_id -> {"cpu_percent", "memory_mb", "ts", "chips": [{...}]}
    node_stats: Dict[int, Dict[str, Any]] = dataclasses.field(
        default_factory=dict)
    # job MFU evidence (SpeedMonitor + ModelInfo FLOPs model); -1 =
    # no FLOPs model reported — rules fall back to raw steps/s
    running_mfu: float = -1.0
    peak_mfu: float = -1.0
    # trailing-window goodput evidence (GoodputLedger.window_summary):
    # {"goodput_fraction", "dominant_badput", "elapsed_rank_seconds",
    #  "window_s", "buckets"}; None = no ledger attached
    goodput: Optional[Dict[str, Any]] = None
    # the running plan's predicted-vs-measured entry
    # (parallel/calibration.py PlanCalibration.current()):
    # {"mesh", "predicted_step_s", "measured_step_s", "ratio",
    #  "samples", ...}; None = no calibration attached / no plan yet
    plan_calibration: Optional[Dict[str, Any]] = None
    # windowed critical-path attribution (master/steptrace.py
    # StepTraceAssembler.summary): {"steps", "by_rank": {rank_str:
    # {"gating_steps", "gating_s", "phases"}}, "dominant_gating_rank",
    # "dominant_gating_phase", "cross_slice_wait_fraction"}; None = no
    # assembler attached / nothing traced yet
    steptrace: Optional[Dict[str, Any]] = None


@dataclasses.dataclass
class DiagnosisReport:
    """One conclusion of the chain (persisted, metered, rendered)."""

    rule: str
    severity: str
    summary: str
    ts: float = 0.0
    worker_id: int = -1
    details: Dict[str, Any] = dataclasses.field(default_factory=dict)
    actions: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, raw: Dict[str, Any]) -> "DiagnosisReport":
        return cls(
            rule=str(raw.get("rule", "")),
            severity=str(raw.get("severity", INFO)),
            summary=str(raw.get("summary", "")),
            ts=float(raw.get("ts", 0.0)),
            worker_id=int(raw.get("worker_id", -1)),
            details=dict(raw.get("details", {})),
            actions=list(raw.get("actions", [])),
        )


def straggler_scores(worker_speeds: Dict[int, WorkerSpeed],
                     min_samples: int = 1) -> Dict[int, float]:
    """score = worker mean step time / median of its PEERS (1.0 = at the
    pack; 2.0 = twice as slow). Leave-one-out deliberately: a median
    that includes the candidate dilutes the signal — in a 2-worker job
    the inclusive score is 2·t1/(t0+t1) < 2 however slow t1 gets, so a
    ratio threshold ≥ 2 could NEVER fire. Workers below ``min_samples``
    are excluded — and so is scoring entirely with < 2 eligible workers
    (a solo worker cannot straggle relative to itself)."""
    eligible = {w: s.mean_step_time_s for w, s in worker_speeds.items()
                if s.samples >= min_samples and s.mean_step_time_s > 0}
    if len(eligible) < 2:
        return {}
    scores = {}
    for worker_id, mean_step in eligible.items():
        peers = [t for w, t in eligible.items() if w != worker_id]
        peer_median = statistics.median(peers)
        if peer_median > 0:
            scores[worker_id] = mean_step / peer_median
    return scores


class Rule:
    name = "rule"

    def evaluate(self, snapshot: DiagnosisSnapshot,
                 ctx: Optional[Context] = None) -> List[DiagnosisReport]:
        raise NotImplementedError


class StragglerRule(Rule):
    """Step-time vs moving median with hysteresis: a rank must score over
    ``straggler_median_ratio`` for ``straggler_trigger_windows``
    consecutive evaluations to be flagged (one slow window — a GC pause,
    a checkpoint — is noise), and under it for
    ``straggler_clear_windows`` to clear. Flagging emits a
    ``profile:{rank}`` action so the evidence (an actual device trace)
    collects itself."""

    name = "straggler"

    def __init__(self):
        self._over: Dict[int, int] = {}     # consecutive over-threshold
        self._under: Dict[int, int] = {}    # consecutive clean (flagged)
        self._flagged: set = set()

    def evaluate(self, snapshot, ctx=None):
        ctx = ctx or Context.singleton()
        scores = straggler_scores(snapshot.worker_speeds,
                                  ctx.diagnosis_min_worker_samples)
        reports: List[DiagnosisReport] = []
        for worker_id, score in scores.items():
            if score > ctx.straggler_median_ratio:
                self._under.pop(worker_id, None)
                count = self._over.get(worker_id, 0) + 1
                self._over[worker_id] = count
                if (worker_id not in self._flagged
                        and count >= ctx.straggler_trigger_windows):
                    self._flagged.add(worker_id)
                    speed = snapshot.worker_speeds[worker_id]
                    reports.append(DiagnosisReport(
                        rule=self.name, severity=WARNING,
                        worker_id=worker_id,
                        summary=(
                            f"worker {worker_id} is a straggler: "
                            f"{speed.mean_step_time_s:.3f}s/step is "
                            f"{score:.2f}x the peer median"),
                        details={"score": round(score, 3),
                                 "mean_step_time_s": round(
                                     speed.mean_step_time_s, 4),
                                 "samples": speed.samples,
                                 "windows_over": count},
                        actions=[f"{ACTION_PROFILE}:{worker_id}",
                                 ACTION_ALERT],
                    ))
            else:
                self._over.pop(worker_id, None)
                if worker_id in self._flagged:
                    count = self._under.get(worker_id, 0) + 1
                    self._under[worker_id] = count
                    if count >= ctx.straggler_clear_windows:
                        self._flagged.discard(worker_id)
                        self._under.pop(worker_id, None)
                        reports.append(DiagnosisReport(
                            rule=self.name, severity=INFO,
                            worker_id=worker_id,
                            summary=(f"worker {worker_id} recovered to "
                                     f"{score:.2f}x the peer median"),
                            details={"score": round(score, 3)},
                            actions=[ACTION_OBSERVE],
                        ))
        # evidence for departed ranks must not linger (a re-joining rank
        # would inherit a half-accumulated hysteresis count)
        live = set(scores)
        for table in (self._over, self._under):
            for worker_id in list(table):
                if worker_id not in live:
                    table.pop(worker_id, None)
        self._flagged &= live | {r.worker_id for r in reports}
        return reports

    @property
    def flagged(self) -> set:
        return set(self._flagged)


class DataPipelineBoundRule(Rule):
    """Data-wait fraction attribution: a worker spending most of its step
    waiting on the input pipeline is starved, not slow — restarting or
    profiling the device would point at the wrong subsystem."""

    name = "data_pipeline_bound"

    def __init__(self):
        self._reported: set = set()

    def evaluate(self, snapshot, ctx=None):
        ctx = ctx or Context.singleton()
        reports: List[DiagnosisReport] = []
        bound = set()
        for worker_id, speed in snapshot.worker_speeds.items():
            if speed.samples < ctx.diagnosis_min_worker_samples:
                continue
            if speed.data_wait_fraction >= ctx.diagnosis_data_wait_fraction:
                bound.add(worker_id)
                if worker_id not in self._reported:
                    self._reported.add(worker_id)
                    reports.append(DiagnosisReport(
                        rule=self.name, severity=WARNING,
                        worker_id=worker_id,
                        summary=(
                            f"worker {worker_id} is data-pipeline bound: "
                            f"{speed.data_wait_fraction:.0%} of step time "
                            f"is data wait"),
                        details={"data_wait_fraction": round(
                            speed.data_wait_fraction, 3),
                            "mean_step_time_s": round(
                                speed.mean_step_time_s, 4)},
                        actions=[ACTION_ALERT],
                    ))
        self._reported &= bound   # re-report if it regresses again later
        return reports


class ThroughputCollapseRule(Rule):
    """Windowed MFU (preferred) or steps/s under
    ``diagnosis_collapse_ratio`` × the world's observed high-water mark.
    MFU is the better collapse signal once a FLOPs model is reported:
    it is what the fleet actually pays for, and a report phrased as
    "MFU 0.18 vs peak 0.63" is directly actionable where raw tokens/s
    needs the model size for context. The peak resets at membership
    change (SpeedMonitor.reset_running_speed), so a deliberate
    scale-down is a new baseline, not a collapse."""

    name = "throughput_collapse"

    def __init__(self):
        self._collapsed = False

    def evaluate(self, snapshot, ctx=None):
        ctx = ctx or Context.singleton()
        if snapshot.peak_mfu > 0.0 and snapshot.running_mfu >= 0.0:
            running, peak = snapshot.running_mfu, snapshot.peak_mfu
            evidence = (f"MFU {running:.3f} vs this world's peak "
                        f"{peak:.3f}")
            details = {"running_mfu": round(running, 4),
                       "peak_mfu": round(peak, 4), "signal": "mfu"}
        else:
            running, peak = snapshot.running_speed, snapshot.peak_speed
            evidence = (f"{running:.2f} vs {peak:.2f} steps/s")
            details = {"running_speed": round(running, 4),
                       "peak_speed": round(peak, 4),
                       "signal": "steps_per_second"}
        if peak <= 0.0 or running <= 0.0:
            return []
        ratio = running / peak
        if ratio < ctx.diagnosis_collapse_ratio:
            if self._collapsed:
                return []
            self._collapsed = True
            details["ratio"] = round(ratio, 3)
            return [DiagnosisReport(
                rule=self.name, severity=CRITICAL,
                summary=(f"throughput collapsed to {ratio:.0%} of this "
                         f"world's peak ({evidence})"),
                details=details,
                actions=[ACTION_ALERT],
            )]
        self._collapsed = False
        return []


class GoodputRule(Rule):
    """Trailing-window goodput under ``goodput_alert_threshold``: the
    job is spending its rank-seconds on something other than productive
    steps, and the report names the dominant badput bucket so the alert
    is actionable (restore-bound vs compile-bound vs data-wait demand
    different fixes). Disabled by default (threshold 0 — an acceptable
    floor is job-specific); the window must be at least
    ``goodput_min_coverage`` covered before judging, so a fresh world's
    first minutes are not evidence."""

    name = "goodput"

    def __init__(self):
        self._alerted = False

    def evaluate(self, snapshot, ctx=None):
        ctx = ctx or Context.singleton()
        threshold = ctx.goodput_alert_threshold
        evidence = snapshot.goodput
        if threshold <= 0.0 or not evidence:
            return []
        window_s = float(evidence.get("window_s", 0.0))
        elapsed = float(evidence.get("elapsed_rank_seconds", 0.0))
        workers = max(1, snapshot.running_workers)
        if window_s <= 0.0 or \
                elapsed < ctx.goodput_min_coverage * window_s * workers:
            return []
        fraction = float(evidence.get("goodput_fraction", -1.0))
        if fraction < 0.0:
            return []
        if fraction < threshold:
            if self._alerted:
                return []
            self._alerted = True
            dominant = evidence.get("dominant_badput") or "idle"
            dominant_s = float(evidence.get("dominant_badput_s", 0.0))
            return [DiagnosisReport(
                rule=self.name, severity=CRITICAL,
                summary=(
                    f"goodput {fraction:.0%} over the last "
                    f"{window_s:.0f}s is below the {threshold:.0%} "
                    f"floor; dominant badput: {dominant} "
                    f"({dominant_s:.0f}s)"),
                details={"goodput_fraction": round(fraction, 4),
                         "threshold": threshold,
                         "window_s": window_s,
                         "dominant_badput": dominant,
                         "dominant_badput_s": round(dominant_s, 1),
                         "buckets": dict(evidence.get("buckets", {}))},
                actions=[ACTION_ALERT],
            )]
        self._alerted = False
        return []


class HbmPressureRule(Rule):
    """Per-chip HBM over the pressure threshold: the next resize or
    batch bump will OOM — warn while there is still headroom to act.

    Judges the PEAK WATERMARK when the chip stats carry one
    (``hbm_peak_mb``, the allocator's in-step high-water mark from
    obs/device.py): the 15 s monitor tick samples ``bytes_in_use``
    BETWEEN steps — the trough — while the transient in-step peak is
    what actually OOMs. The per-rank step-report watermark
    (``hbm_peak_mb`` on the node entry) is folded in too; the trough
    remains the fallback for senders predating the field."""

    name = "hbm_pressure"

    def __init__(self):
        self._reported: set = set()

    def evaluate(self, snapshot, ctx=None):
        ctx = ctx or Context.singleton()
        reports: List[DiagnosisReport] = []
        pressured = set()
        for worker_id, stats in snapshot.node_stats.items():
            worst = 0.0
            signal = "bytes_in_use"
            max_total = 0.0
            for chip in stats.get("chips", ()):
                total = float(chip.get("hbm_total_mb", 0.0) or 0.0)
                if total <= 0:
                    continue
                max_total = max(max_total, total)
                peak = float(chip.get("hbm_peak_mb", -1.0) or -1.0)
                if peak >= 0.0:
                    used, chip_signal = peak, "peak_watermark"
                else:
                    used = float(chip.get("hbm_used_mb", 0.0))
                    chip_signal = "bytes_in_use"
                pct = 100.0 * used / total
                if pct > worst:
                    worst, signal = pct, chip_signal
            # the step report's device-truth window peak (report-interval
            # cadence — fresher than the chip-stats file relay)
            node_peak = float(stats.get("hbm_peak_mb", -1.0) or -1.0)
            if node_peak >= 0.0 and max_total > 0:
                pct = 100.0 * node_peak / max_total
                if pct > worst:
                    worst, signal = pct, "step_peak_watermark"
            if worst >= ctx.diagnosis_hbm_pressure_pct:
                pressured.add(worker_id)
                if worker_id not in self._reported:
                    self._reported.add(worker_id)
                    reports.append(DiagnosisReport(
                        rule=self.name, severity=WARNING,
                        worker_id=worker_id,
                        summary=(f"worker {worker_id} HBM pressure: "
                                 f"{worst:.1f}% of a chip's HBM "
                                 f"({signal})"),
                        details={"worst_chip_pct": round(worst, 2),
                                 "signal": signal},
                        actions=[ACTION_ALERT],
                    ))
        self._reported &= pressured
        return reports


class PlanRegressionRule(Rule):
    """Measured step time exceeds the planner's prediction for the
    RUNNING plan by ``plan_regression_ratio`` — the plan the fleet is
    executing is slower than what it was chosen FOR, so the planner's
    ranking (and every future resize decision scored with the same
    prior) is suspect. Hysteresis like StragglerRule: the ratio must
    hold for ``plan_regression_windows`` consecutive diagnosis rounds
    (one slow window — a checkpoint, a GC pause — is noise), and fall
    under for ``plan_regression_clear_windows`` to clear. A signature
    change (a new plan applied) resets the evidence: the new shape is
    judged on its own measurements. The calibration loop
    (parallel/calibration.py) feeds the per-axis discounts back into
    scoring either way; this rule is the ALERT that the loop had to
    correct by more than the configured ratio."""

    name = "plan_regression"

    def __init__(self):
        self._signature = ""
        self._over = 0
        self._under = 0
        self._alerted = False

    def evaluate(self, snapshot, ctx=None):
        ctx = ctx or Context.singleton()
        ratio_floor = ctx.plan_regression_ratio
        entry = snapshot.plan_calibration
        if ratio_floor <= 0.0 or not entry:
            return []
        if entry.get("signature", "") != self._signature:
            self._signature = str(entry.get("signature", ""))
            self._over = self._under = 0
            self._alerted = False
        predicted = float(entry.get("predicted_step_s", 0.0))
        measured = float(entry.get("measured_step_s", 0.0))
        samples = int(entry.get("samples", 0))
        if predicted <= 0.0 or measured <= 0.0 \
                or samples < ctx.calibration_min_samples:
            return []
        ratio = measured / predicted
        if ratio > ratio_floor:
            self._under = 0
            self._over += 1
            if not self._alerted \
                    and self._over >= ctx.plan_regression_windows:
                self._alerted = True
                mesh = entry.get("mesh", {})
                return [DiagnosisReport(
                    rule=self.name, severity=WARNING,
                    summary=(
                        f"plan regression: measured {measured:.3f}s/"
                        f"step is {ratio:.2f}x the planner's "
                        f"{predicted:.3f}s prediction for mesh "
                        f"{mesh} ({samples} windowed samples)"),
                    details={"ratio": round(ratio, 3),
                             "predicted_step_s": round(predicted, 6),
                             "measured_step_s": round(measured, 6),
                             "samples": samples,
                             "mesh": dict(mesh),
                             "windows_over": self._over},
                    actions=[ACTION_ALERT],
                )]
            return []
        self._over = 0
        if self._alerted:
            self._under += 1
            if self._under >= ctx.plan_regression_clear_windows:
                self._alerted = False
                self._under = 0
                return [DiagnosisReport(
                    rule=self.name, severity=INFO,
                    summary=(f"plan regression cleared: measured step "
                             f"time back to {ratio:.2f}x prediction"),
                    details={"ratio": round(ratio, 3)},
                    actions=[ACTION_OBSERVE],
                )]
        return []


class CriticalPathRule(Rule):
    """Steptrace critical-path attribution: a rank that GATES the fleet
    step — the one every other rank was waiting on — for at least
    ``critical_path_gating_fraction`` of the traced window is flagged by
    the *seconds it cost*, not by a mean ratio. This is sharper than
    :class:`StragglerRule`: a rank can have an unremarkable mean step
    time and still gate every step (it is last by a little, every
    time), and the evidence names the PHASE that gated (compute vs
    data_wait vs checkpoint), so the profile request already knows what
    it is looking for. Hysteresis mirrors StragglerRule
    (``straggler_trigger_windows`` to flag,
    ``straggler_clear_windows`` to clear); disabled when the fraction
    knob is <= 0 or the window has fewer than
    ``diagnosis_min_worker_samples`` traced steps."""

    name = "critical_path"

    def __init__(self):
        self._over: Dict[int, int] = {}     # consecutive over-threshold
        self._under: Dict[int, int] = {}    # consecutive clean (flagged)
        self._flagged: set = set()

    def evaluate(self, snapshot, ctx=None):
        ctx = ctx or Context.singleton()
        threshold = ctx.critical_path_gating_fraction
        evidence = snapshot.steptrace
        if threshold <= 0.0 or not evidence:
            return []
        steps = int(evidence.get("steps", 0))
        if steps < ctx.diagnosis_min_worker_samples:
            return []
        by_rank = evidence.get("by_rank", {}) or {}
        reports: List[DiagnosisReport] = []
        live = set()
        for rank_key, entry in by_rank.items():
            try:
                worker_id = int(rank_key)
            except (TypeError, ValueError):
                continue
            live.add(worker_id)
            gating_steps = int(entry.get("gating_steps", 0))
            gating_s = float(entry.get("gating_s", 0.0))
            fraction = gating_steps / steps
            phases = entry.get("phases", {}) or {}
            dominant_phase = max(
                sorted(phases), key=lambda p: phases[p],
                default="unknown")
            if fraction >= threshold:
                self._under.pop(worker_id, None)
                count = self._over.get(worker_id, 0) + 1
                self._over[worker_id] = count
                if (worker_id not in self._flagged
                        and count >= ctx.straggler_trigger_windows):
                    self._flagged.add(worker_id)
                    reports.append(DiagnosisReport(
                        rule=self.name, severity=WARNING,
                        worker_id=worker_id,
                        summary=(
                            f"rank {worker_id} gated {gating_steps}/"
                            f"{steps} traced steps "
                            f"({dominant_phase}, {gating_s:.2f}s "
                            f"gating)"),
                        details={
                            "gating_steps": gating_steps,
                            "traced_steps": steps,
                            "gating_fraction": round(fraction, 3),
                            "gating_s": round(gating_s, 4),
                            "gating_phase": dominant_phase,
                            "phases": {p: round(float(s), 4)
                                       for p, s in phases.items()},
                            "windows_over": count},
                        actions=[f"{ACTION_PROFILE}:{worker_id}",
                                 ACTION_ALERT],
                    ))
            else:
                self._over.pop(worker_id, None)
                if worker_id in self._flagged:
                    count = self._under.get(worker_id, 0) + 1
                    self._under[worker_id] = count
                    if count >= ctx.straggler_clear_windows:
                        self._flagged.discard(worker_id)
                        self._under.pop(worker_id, None)
                        reports.append(DiagnosisReport(
                            rule=self.name, severity=INFO,
                            worker_id=worker_id,
                            summary=(
                                f"rank {worker_id} off the critical "
                                f"path: gated {gating_steps}/{steps} "
                                f"traced steps"),
                            details={"gating_fraction": round(
                                fraction, 3)},
                            actions=[ACTION_OBSERVE],
                        ))
        # evidence for departed ranks must not linger (a re-joining rank
        # would inherit a half-accumulated hysteresis count)
        for table in (self._over, self._under):
            for worker_id in list(table):
                if worker_id not in live:
                    table.pop(worker_id, None)
        self._flagged &= live | {r.worker_id for r in reports}
        return reports

    @property
    def flagged(self) -> set:
        return set(self._flagged)


def default_rules() -> List[Rule]:
    """The chain, cheapest-evidence first."""
    return [StragglerRule(), CriticalPathRule(), DataPipelineBoundRule(),
            ThroughputCollapseRule(), HbmPressureRule(),
            PlanRegressionRule(), GoodputRule()]


def parse_action(action: str) -> Dict[str, Any]:
    """``kind[:rank]`` → {"kind", "rank"}; unknown kinds map to observe
    (an old agent must never crash on a newer master's grammar)."""
    kind, _, rank = action.partition(":")
    kind = kind.strip().lower()
    if kind not in (ACTION_OBSERVE, ACTION_PROFILE, ACTION_RESTART,
                    ACTION_CHECKPOINT, ACTION_DRAIN, ACTION_ALERT):
        kind = ACTION_OBSERVE
    try:
        target = int(rank) if rank else -1
    except ValueError:
        target = -1
    return {"kind": kind, "rank": target}
