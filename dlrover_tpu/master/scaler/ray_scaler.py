"""Scaler actuating ScalePlans as Ray agent actors.

Capability parity: the reference's ray path — RayClient/RayElasticJob
(dlrover/python/scheduler/ray.py:51,147) actuated from the master, with
TFRayWorker-style actors (trainer/worker/tf_ray_worker.py) playing the
node role. Each "node" is one ElasticAgent actor that joins the master
rendezvous exactly like a pod-hosted agent.
"""

from __future__ import annotations

import shlex
from typing import Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler
from dlrover_tpu.scheduler.ray import RayClient


class RayScaler(Scaler):
    def __init__(self, job_name: str, client: RayClient,
                 master_addr: str = "", command: str = ""):
        super().__init__(job_name)
        self._client = client
        self._master_addr = master_addr
        self._command = command

    def _entrypoint(self, node: Node):
        if not self._command:
            raise ValueError(
                "ray platform needs the job command (JobArgs.command) to "
                "build the agent entrypoint")
        return shlex.split(self._command)

    def _create(self, node: Node) -> None:
        self.register_existing(node.type, node.id + 1)
        self._client.create_agent_actor(
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            master_addr=self._master_addr,
            entrypoint=self._entrypoint(node),
            num_cpus=node.config_resource.cpu or 1.0,
        )

    def scale(self, plan: ScalePlan) -> None:
        for node in plan.remove_nodes:
            logger.info("ray scaler: removing %s", node.name)
            self._client.delete_actor(node.name)
        group_total: Optional[int] = None
        for node_type, group in plan.node_group_resources.items():
            existing = [h for h in self._client.list_actors()
                        if h.node_type == node_type]
            group_total = group.count
            delta = group.count - len(existing)
            if delta > 0:
                ranks = self.fill_rank_holes(
                    (h.rank_index for h in existing), group.count, delta)
                for rank in ranks:
                    self._create(Node(
                        node_type, self.alloc_id(node_type),
                        rank_index=rank,
                        config_resource=group.node_resource))
            elif delta < 0:
                doomed = sorted(existing,
                                key=lambda h: -h.rank_index)[:(-delta)]
                for handle in doomed:
                    logger.info("ray scaler: scaling down %s", handle.name)
                    self._client.delete_actor(handle.name)
        for node in plan.launch_nodes:
            self._create(node)
