"""Scale-plan actuation (reference: dlrover/python/master/scaler/)."""

from dlrover_tpu.master.scaler.base import ScalePlan, Scaler
from dlrover_tpu.master.scaler.local_scaler import LocalScaler

__all__ = ["ScalePlan", "Scaler", "LocalScaler"]
