"""K8s pod scaler: ScalePlan → pod create/delete with retry queue.

Capability parity: PodScaler (dlrover/python/master/scaler/
pod_scaler.py:130,325,352) — a background thread drains a creation queue so
transient API errors retry, pods carry the framework env contract, and
scale-down removes the highest ranks first.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, Optional

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler
from dlrover_tpu.scheduler.kubernetes import (
    K8sClient,
    build_pod_manifest,
    pod_to_fields,
)


class PodScaler(Scaler):
    def __init__(
        self,
        job_name: str,
        client: K8sClient,
        master_addr: str,
        image: str = "",
        command: str = "",
        tpu_topology: str = "",
        owner_ref: Optional[Dict] = None,
        retry_interval_s: float = 3.0,
    ):
        super().__init__(job_name)
        self._client = client
        self._master_addr = master_addr
        self._image = image
        self._command = command
        self._tpu_topology = tpu_topology
        self._owner_ref = owner_ref
        self._retry_interval_s = retry_interval_s
        self._create_queue: "queue.Queue[Node]" = queue.Queue()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._node_num: Dict[str, int] = {}

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._periodic_create_pod, daemon=True,
            name="pod-creater")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _periodic_create_pod(self) -> None:
        """Drain the creation queue; failed creates are re-queued
        (reference: _periodic_create_pod, pod_scaler.py:325)."""
        while not self._stopped.is_set():
            try:
                node = self._create_queue.get(timeout=1.0)
            except queue.Empty:
                continue
            manifest = build_pod_manifest(
                job_name=self.job_name,
                node_type=node.type,
                node_id=node.id,
                rank_index=node.rank_index,
                image=self._image,
                command=self._command,
                master_addr=self._master_addr,
                node_num=self._node_num.get(node.type, node.rank_index + 1),
                resource=node.config_resource,
                tpu_topology=self._tpu_topology,
                owner_ref=self._owner_ref,
            )
            if not self._client.create_pod(manifest):
                logger.warning("pod create failed for %s; will retry",
                               node.name)
                time.sleep(self._retry_interval_s)
                self._create_queue.put(node)

    def scale(self, plan: ScalePlan) -> None:
        for node in plan.remove_nodes:
            self._client.delete_pod(node.name)
        for node_type, group in plan.node_group_resources.items():
            self._node_num[node_type] = group.count
            live = []
            for raw in self._client.list_pods(
                    f"dlrover-tpu/job={self.job_name},"
                    f"dlrover-tpu/type={node_type}"):
                fields = pod_to_fields(raw)
                if fields["status"] in (NodeStatus.PENDING,
                                        NodeStatus.RUNNING):
                    live.append(fields)
            delta = group.count - len(live)
            if delta > 0:
                ranks = self.fill_rank_holes(
                    (f["rank_index"] for f in live), group.count, delta)
                for rank in ranks:
                    node = Node(node_type, self.alloc_id(node_type),
                                rank_index=rank,
                                config_resource=group.node_resource)
                    self._create_queue.put(node)
            elif delta < 0:
                for fields in sorted(
                        live, key=lambda f: -f["rank_index"])[:(-delta)]:
                    self._client.delete_pod(fields["name"])
        for node in plan.launch_nodes:
            self.register_existing(node.type, node.id + 1)
            self._create_queue.put(node)
