"""Scaler against the in-memory LocalCluster.

The local analog of PodScaler (reference: master/scaler/pod_scaler.py:130):
creates/deletes PodRecords, carrying the same env contract the k8s path
injects into containers.
"""

from __future__ import annotations

from typing import Optional

from dlrover_tpu.common.constants import NodeEnv, NodeStatus
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler
from dlrover_tpu.scheduler.local import LocalCluster, PodRecord


class LocalScaler(Scaler):
    def __init__(self, job_name: str, cluster: LocalCluster,
                 master_addr: str = ""):
        super().__init__(job_name)
        self._cluster = cluster
        self._master_addr = master_addr

    def _create(self, node: Node, node_num: int) -> None:
        self.register_existing(node.type, node.id + 1)
        pod = PodRecord(
            name=node.name,
            node_type=node.type,
            node_id=node.id,
            rank_index=node.rank_index,
            status=NodeStatus.PENDING,
            env={
                NodeEnv.MASTER_ADDR: self._master_addr,
                NodeEnv.NODE_ID: str(node.id),
                NodeEnv.NODE_RANK: str(node.rank_index),
                NodeEnv.NODE_NUM: str(node_num),
                NodeEnv.JOB_NAME: self.job_name,
            },
            resource=node.config_resource.to_dict(),
        )
        self._cluster.create_pod(pod)

    def scale(self, plan: ScalePlan) -> None:
        for node in plan.remove_nodes:
            logger.info("scaler: removing %s", node.name)
            self._cluster.delete_pod(node.name)
        group_total: Optional[int] = None
        for node_type, group in plan.node_group_resources.items():
            existing = [p for p in self._cluster.list_pods(node_type)
                        if p.status not in
                        (NodeStatus.FAILED, NodeStatus.DELETED,
                         NodeStatus.SUCCEEDED)]
            group_total = group.count
            delta = group.count - len(existing)
            if delta > 0:
                ranks = self.fill_rank_holes(
                    (p.rank_index for p in existing), group.count, delta)
                for rank in ranks:
                    node = Node(node_type, self.alloc_id(node_type),
                                rank_index=rank,
                                config_resource=group.node_resource)
                    self._create(node, group.count)
            elif delta < 0:
                # remove highest-rank pods first (keeps ranks contiguous)
                doomed = sorted(existing, key=lambda p: -p.rank_index)[:(-delta)]
                for pod in doomed:
                    logger.info("scaler: scaling down %s", pod.name)
                    self._cluster.delete_pod(pod.name)
        for node in plan.launch_nodes:
            self._create(node, group_total or (node.rank_index + 1))
