"""ScalePlan model + Scaler interface.

Capability parity: dlrover/python/master/scaler/base_scaler.py — a plan
names the target group sizes plus explicit node launches/removals; a
Scaler actuates it against the platform.
"""

from __future__ import annotations

import abc
import threading
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.node import Node, NodeGroupResource


@dataclass
class ScalePlan:
    # Target per-type group size/resource ("scale to N workers of shape R").
    node_group_resources: Dict[str, NodeGroupResource] = field(
        default_factory=dict)
    # Explicit node launches (relaunches carry rank/config of the dead node).
    launch_nodes: List[Node] = field(default_factory=list)
    # Explicit removals.
    remove_nodes: List[Node] = field(default_factory=list)

    def empty(self) -> bool:
        return (not self.node_group_resources and not self.launch_nodes
                and not self.remove_nodes)

    def merge(self, other: "ScalePlan") -> None:
        self.node_group_resources.update(other.node_group_resources)
        self.launch_nodes.extend(other.launch_nodes)
        self.remove_nodes.extend(other.remove_nodes)


class Scaler(abc.ABC):
    """Actuates ScalePlans (reference: Scaler base, pod_scaler.py:71)."""

    def __init__(self, job_name: str):
        self.job_name = job_name
        self._id_lock = threading.Lock()
        self._next_id: Dict[str, int] = {}

    @abc.abstractmethod
    def scale(self, plan: ScalePlan) -> None:
        ...

    def start(self) -> None:  # pragma: no cover - default no-op
        pass

    def stop(self) -> None:  # pragma: no cover - default no-op
        pass

    # -- node-id allocation (shared by all backends) --------------------
    def alloc_id(self, node_type: str) -> int:
        with self._id_lock:
            next_id = self._next_id.get(node_type, 0)
            self._next_id[node_type] = next_id + 1
            return next_id

    def register_existing(self, node_type: str, upto_id: int) -> None:
        """Keep the allocator ahead of externally-assigned ids (manager
        relaunch ids) so a group-grow never reuses a live pod name."""
        with self._id_lock:
            self._next_id[node_type] = max(
                self._next_id.get(node_type, 0), upto_id)

    @staticmethod
    def fill_rank_holes(used_ranks, count: int, needed: int) -> List[int]:
        """Ranks for `needed` new nodes: lowest free ranks below `count`
        first (a relaunched node keeps its rank, so grows must fill the
        holes), then sequential past the end."""
        used = set(used_ranks)
        free = [r for r in range(count) if r not in used]
        ranks = free[:needed]
        rank = count
        while len(ranks) < needed:
            if rank not in used:
                ranks.append(rank)
            rank += 1
        return ranks
