"""ScalePlan model + Scaler interface.

Capability parity: dlrover/python/master/scaler/base_scaler.py — a plan
names the target group sizes plus explicit node launches/removals; a
Scaler actuates it against the platform.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, List

from dlrover_tpu.common.node import Node, NodeGroupResource


@dataclass
class ScalePlan:
    # Target per-type group size/resource ("scale to N workers of shape R").
    node_group_resources: Dict[str, NodeGroupResource] = field(
        default_factory=dict)
    # Explicit node launches (relaunches carry rank/config of the dead node).
    launch_nodes: List[Node] = field(default_factory=list)
    # Explicit removals.
    remove_nodes: List[Node] = field(default_factory=list)

    def empty(self) -> bool:
        return (not self.node_group_resources and not self.launch_nodes
                and not self.remove_nodes)

    def merge(self, other: "ScalePlan") -> None:
        self.node_group_resources.update(other.node_group_resources)
        self.launch_nodes.extend(other.launch_nodes)
        self.remove_nodes.extend(other.remove_nodes)


class Scaler(abc.ABC):
    """Actuates ScalePlans (reference: Scaler base, pod_scaler.py:71)."""

    def __init__(self, job_name: str):
        self.job_name = job_name

    @abc.abstractmethod
    def scale(self, plan: ScalePlan) -> None:
        ...

    def start(self) -> None:  # pragma: no cover - default no-op
        pass

    def stop(self) -> None:  # pragma: no cover - default no-op
        pass
