"""StepTraceAssembler: join per-step trace records, solve the critical
path, name the rank and phase that gated every fleet step.

Workers emit compact per-step trace records (obs/steptrace.py — the wire
contract lives there) over the TelemetryReport channel; the servicer
feeds them here. Records are joined by ``(generation, step)`` into a
bounded ring of groups; each group is solved into one critical-path
attribution:

- every rank's record is aligned onto the master clock via its stamped
  offset (``t0 + off``),
- the *tail* rank (latest aligned step end) anchors the walk,
- if the tail rank's dominant phase is ``cross_slice_wait`` the walk
  follows the slowest input edge of the barrier join — the peer slice
  whose gradient header was observed last — and attributes *that*
  slice's dominant pre-post phase instead (one hop: the barrier chain
  has a single cross-slice join per step).

So a chaos-delayed slice is named by its own compute time even though
only the *surviving* slice's record shows the wait.

Three consumers: the tsdb series (gating rank / gating seconds by phase
/ cross-slice-wait fraction), the CriticalPathRule in the diagnosis
engine (gating *seconds* instead of mean-ratio), and rendering
(`tools/steptrace.py` waterfall + chrome-trace export, `tools/top.py`
panel, the stop-time flight embed). The query payload is pure JSON so
the waterfall renders byte-identically from the live RPC and from a
flight dump.

stdlib-only by design (imported by tools and benches without jax).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.config import Context
from dlrover_tpu.obs.steptrace import phase_seconds

STEPTRACE_PAYLOAD_VERSION = 1

# phases eligible for attribution after the walk hops the barrier edge:
# the gated side's wait must never be re-attributed as the gating
# slice's wait (one hop, no ping-pong)
_HOP_EXCLUDED = ("cross_slice_wait",)


def _sorted_argmax(items: Dict[str, float]) -> Tuple[str, float]:
    """Deterministic argmax: ties go to the lexicographically first key
    (solves must render byte-identically across runs)."""
    best = max(sorted(items.items()), key=lambda kv: kv[1])
    return best[0], best[1]


def solve_group(gen: int, step: int,
                recs: Dict[int, Dict[str, Any]]) -> Dict[str, Any]:
    """One group's critical-path attribution (pure function of the
    records — benches call this without an assembler). All dict keys in
    the result are strings: the payload must survive a JSON round trip
    unchanged (live RPC and flight dump render identical bytes)."""
    lanes: List[Dict[str, Any]] = []
    ends: Dict[int, float] = {}
    starts: Dict[int, float] = {}
    durs_by_rank: Dict[int, Dict[str, float]] = {}
    for rank in sorted(recs):
        rec = recs[rank]
        base = float(rec.get("t0", 0.0)) + float(rec.get("off", 0.0))
        segs = []
        end_off = 0.0
        for seg in rec.get("phases") or []:
            try:
                name, start, dur = str(seg[0]), float(seg[1]), float(seg[2])
            except (TypeError, ValueError, IndexError):
                continue
            segs.append([name, round(start, 6), round(max(0.0, dur), 6)])
            end_off = max(end_off, start + max(0.0, dur))
        starts[rank] = base
        ends[rank] = base + end_off
        durs_by_rank[rank] = phase_seconds(rec)
        lanes.append({
            "rank": rank,
            "slice": int(rec.get("slice", -1)),
            "start": round(base, 6),
            "err": float(rec.get("err", -1.0)),
            "phases": segs,
            "peers": {str(k): float(v)
                      for k, v in (rec.get("peers") or {}).items()},
        })
    if not lanes:
        return {}
    t_min = min(starts.values())
    t_max = max(ends.values())
    # anchor: the tail rank (latest aligned end; ties to lowest rank)
    tail_rank = min(r for r in ends if ends[r] == t_max)
    tail_rec = recs[tail_rank]
    tail_durs = durs_by_rank[tail_rank]
    gating_rank, hopped = tail_rank, False
    gating_phase, gating_s = (_sorted_argmax(tail_durs)
                              if tail_durs else ("", 0.0))
    if gating_phase == "cross_slice_wait":
        peers = tail_rec.get("peers") or {}
        if peers:
            # slowest input edge of the join: the last-observed peer
            last_sid, _ = _sorted_argmax(
                {str(k): float(v) for k, v in peers.items()})
            try:
                last_sid_i = int(last_sid)
            except ValueError:
                last_sid_i = -1
            if last_sid_i != int(tail_rec.get("slice", -1)):
                cands = [r for r in sorted(recs)
                         if int(recs[r].get("slice", -2)) == last_sid_i]
                if cands:
                    peer_rank = max(cands, key=lambda r: (ends[r], -r))
                    pdurs = {k: v
                             for k, v in durs_by_rank[peer_rank].items()
                             if k not in _HOP_EXCLUDED}
                    if pdurs:
                        gating_rank, hopped = peer_rank, True
                        gating_phase, gating_s = _sorted_argmax(pdurs)
    span_s = max(0.0, t_max - t_min)
    cross_wait = max((d.get("cross_slice_wait", 0.0)
                      for d in durs_by_rank.values()), default=0.0)
    errs = [ln["err"] for ln in lanes if ln["err"] >= 0.0]
    return {
        "step": int(step),
        "gen": int(gen),
        "t0": round(t_min, 6),
        "span_s": round(span_s, 6),
        "gating_rank": int(gating_rank),
        "gating_phase": gating_phase,
        "gating_s": round(gating_s, 6),
        "hopped": hopped,
        "cross_slice_wait_s": round(cross_wait, 6),
        "cross_slice_wait_fraction": round(
            cross_wait / span_s if span_s > 0 else 0.0, 6),
        "clock_err_max": round(max(errs), 6) if errs else -1.0,
        "lanes": lanes,
    }


def summarize_solved(solved: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Windowed attribution over solved groups: gating share per rank,
    dominant phase, mean cross-slice-wait fraction. Pure (benches fold
    this shape into their JSON)."""
    by_rank: Dict[str, Dict[str, Any]] = {}
    frac_sum = 0.0
    for group in solved:
        if not group:
            continue
        rank = str(group.get("gating_rank", -1))
        entry = by_rank.setdefault(
            rank, {"gating_steps": 0, "gating_s": 0.0, "phases": {}})
        entry["gating_steps"] += 1
        entry["gating_s"] = round(
            entry["gating_s"] + float(group.get("gating_s", 0.0)), 6)
        phase = str(group.get("gating_phase", ""))
        entry["phases"][phase] = round(
            entry["phases"].get(phase, 0.0)
            + float(group.get("gating_s", 0.0)), 6)
        frac_sum += float(group.get("cross_slice_wait_fraction", 0.0))
    steps = sum(e["gating_steps"] for e in by_rank.values())
    dominant_phase, dominant_rank = "", -1
    if by_rank:
        rank_str, _ = _sorted_argmax(
            {r: float(e["gating_steps"]) for r, e in by_rank.items()})
        dominant_rank = int(rank_str)
        phases: Dict[str, float] = {}
        for entry in by_rank.values():
            for phase, secs in entry["phases"].items():
                phases[phase] = phases.get(phase, 0.0) + secs
        if phases:
            dominant_phase, _ = _sorted_argmax(phases)
    return {
        "steps": steps,
        "by_rank": by_rank,
        "dominant_gating_rank": dominant_rank,
        "dominant_gating_phase": dominant_phase,
        "cross_slice_wait_fraction": round(
            frac_sum / steps if steps else -1.0, 6),
    }


class StepTraceAssembler:
    """Bounded ring of per-step record groups + cached solves.

    Ingest runs on the telemetry drainer thread (already off the RPC
    hot path); solving a group is a few dict scans, tsdb feeds are
    in-memory. Groups older than the newest step seen are published to
    the tsdb exactly once (records for a step keep arriving while the
    fleet runs the next one — publishing on arrival would emit half
    -joined attributions)."""

    def __init__(self, tsdb=None, registry=None,
                 ring_steps: Optional[int] = None,
                 summary_window: int = 64):
        self._lock = threading.Lock()
        self._tsdb = tsdb
        self._registry = registry or obs.get_registry()
        self._ring_steps = max(
            1, int(ring_steps if ring_steps is not None
                   else Context.singleton().steptrace_ring_steps))
        self._summary_window = max(1, int(summary_window))
        # (gen, step) -> {"recs": {rank: record}, "published": bool,
        #                 "solved": Optional[dict]}
        self._groups: "OrderedDict[Tuple[int, int], Dict[str, Any]]" = (
            OrderedDict())
        self._records_total = 0
        self._dropped = 0

    # -- ingest ------------------------------------------------------------
    def ingest(self, records: List[Any], node_rank: int = -1) -> int:
        """Join a telemetry batch; returns how many records were
        accepted. Malformed records are counted and dropped, never
        raised — the wire is telemetry."""
        accepted = 0
        with self._lock:
            for rec in records or []:
                if not self._ingest_one(rec, node_rank):
                    self._dropped += 1
                    continue
                accepted += 1
                self._records_total += 1
            if accepted:
                self._publish_older_locked()
        try:
            if accepted:
                self._registry.counter(
                    "dlrover_tpu_steptrace_records_total",
                    "Per-step trace records joined by the assembler",
                ).inc(accepted)
            if records and accepted < len(records):
                self._registry.counter(
                    "dlrover_tpu_steptrace_dropped_total",
                    "Malformed per-step trace records dropped at ingest",
                ).inc(len(records) - accepted)
        except Exception:  # noqa: BLE001 — telemetry must never raise
            pass
        return accepted

    def _ingest_one(self, rec: Any, node_rank: int) -> bool:
        if not isinstance(rec, dict):
            return False
        try:
            step = int(rec["step"])
            gen = int(rec.get("gen", 0))
            rank = int(rec.get("rank", -1))
        except (KeyError, TypeError, ValueError):
            return False
        if rank < 0:
            rank = int(node_rank)
        if step < 0 or rank < 0:
            return False
        if not isinstance(rec.get("phases"), list):
            return False
        key = (gen, step)
        group = self._groups.get(key)
        if group is None:
            group = {"recs": {}, "published": False, "solved": None}
            self._groups[key] = group
            while len(self._groups) > self._ring_steps:
                self._groups.popitem(last=False)
        group["recs"][rank] = rec
        group["solved"] = None  # new member invalidates the cached solve
        return True

    def _publish_older_locked(self) -> None:
        if self._tsdb is None:
            return
        newest = max(self._groups)
        for key, group in self._groups.items():
            if group["published"] or key >= newest:
                continue
            group["published"] = True
            solved = self._solve_locked(key, group)
            if not solved:
                continue
            self._tsdb.ingest("dlrover_tpu_steptrace_gating_rank",
                              float(solved["gating_rank"]))
            self._tsdb.ingest(
                "dlrover_tpu_steptrace_gating_seconds",
                float(solved["gating_s"]),
                labels={"phase": solved["gating_phase"] or "unknown"})
            self._tsdb.ingest(
                "dlrover_tpu_steptrace_cross_slice_wait_fraction",
                float(solved["cross_slice_wait_fraction"]))

    def _solve_locked(self, key: Tuple[int, int],
                      group: Dict[str, Any]) -> Dict[str, Any]:
        if group["solved"] is None:
            group["solved"] = solve_group(key[0], key[1], group["recs"])
        return group["solved"]

    # -- queries -----------------------------------------------------------
    def query_payload(self, start_step: int = -1, end_step: int = -1,
                      last_n: int = 0) -> Dict[str, Any]:
        """Assembled steps + windowed summary as pure JSON (the single
        shape tools/steptrace.py renders — live RPC and the flight embed
        must stay byte-identical through it)."""
        with self._lock:
            keys = sorted(self._groups)
            if start_step >= 0:
                keys = [k for k in keys if k[1] >= start_step]
            if end_step >= 0:
                keys = [k for k in keys if k[1] <= end_step]
            if last_n > 0:
                keys = keys[-last_n:]
            solved = [self._solve_locked(k, self._groups[k]) for k in keys]
            window = [self._solve_locked(k, self._groups[k])
                      for k in sorted(self._groups)[-self._summary_window:]]
        solved = [s for s in solved if s]
        return {
            "version": STEPTRACE_PAYLOAD_VERSION,
            "steps": solved,
            "summary": summarize_solved([s for s in window if s]),
        }

    def summary(self) -> Dict[str, Any]:
        """The windowed attribution alone (DiagnosisSnapshot evidence)."""
        with self._lock:
            window = [self._solve_locked(k, self._groups[k])
                      for k in sorted(self._groups)[-self._summary_window:]]
        return summarize_solved([s for s in window if s])

    def flight_snapshot(self, last_n: int = 128) -> Dict[str, Any]:
        """The stop-time flight embed: the same payload the live RPC
        serves, so a postmortem waterfall renders byte-identically from
        the dump."""
        return self.query_payload(last_n=last_n)

    def evict(self, rank: int) -> None:
        """A reaped rank's records leave every retained group (mirrors
        the servicer's speed/diagnosis eviction): a departed worker must
        not keep gating history it can no longer update."""
        with self._lock:
            for group in self._groups.values():
                if group["recs"].pop(int(rank), None) is not None:
                    group["solved"] = None

    def evict_departed(self, live) -> None:
        """Evict every rank not in ``live`` (the servicer's post-reap
        sweep — same contract as SpeedMonitor.evict_departed)."""
        alive = {int(r) for r in live}
        with self._lock:
            seen = set()
            for group in self._groups.values():
                seen.update(group["recs"])
        for rank in sorted(seen - alive):
            self.evict(rank)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"groups": len(self._groups),
                    "records_total": self._records_total,
                    "dropped": self._dropped}
