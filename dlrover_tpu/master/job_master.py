"""Job master: composition root + serving loop.

Capability parity: dlrover/python/master/local_master.py:38 (LocalJobMaster)
and dist_master.py:53 (DistributedJobMaster composition :62-71, 30 s watch
loop :165-222). The master owns every control-plane component and runs the
gRPC service; `prepare()` starts serving, `run()` polls for job completion /
hang; the node manager (when attached) owns node lifecycle.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dlrover_tpu import obs
from dlrover_tpu.common.comm import build_server
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import JobStage, NodeType, RendezvousName
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.state_backend import MasterStateBackend, MutationLog
from dlrover_tpu.master.rendezvous import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousParameters,
)
from dlrover_tpu.master.rendezvous_shards import ShardedRendezvousManager
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.sync_service import ElasticPsService, SyncService


class JobMaster:
    """One instance per job. With no node manager attached this is the
    standalone/local master (the `dlrover-run --standalone` equivalent)."""

    def __init__(
        self,
        port: int = 0,
        min_nodes: int = 1,
        max_nodes: int = 1,
        node_unit: int = 1,
        job_manager=None,
        job_args=None,
        cluster=None,
        host: str = "0.0.0.0",
        brain_addr: str = "",
        state_dir: Optional[str] = None,
        preloaded_state: Optional[tuple] = None,
    ):
        ctx = Context.singleton()
        params = RendezvousParameters(
            min_nodes=min_nodes,
            max_nodes=max_nodes,
            wait_new_node_s=ctx.rdzv_wait_new_node_s,
            node_unit=node_unit,
        )
        self.task_manager = TaskManager()
        self.speed_monitor = SpeedMonitor()
        self.task_manager.speed_monitor = self.speed_monitor
        # sharded by default: per-slice rendezvous shards behind a thin
        # router, so one slice's join storm (or a wedged shard) can
        # never delay another slice's cut (rendezvous_shards.py).
        # rdzv_sharded=False keeps the single-lock manager — the bench
        # baseline and an escape hatch.
        training_mgr = (ShardedRendezvousManager(params)
                        if ctx.rdzv_sharded
                        else ElasticTrainingRendezvousManager(params))
        self.rdzv_managers = {
            RendezvousName.TRAINING: training_mgr,
            RendezvousName.NETWORK_CHECK:
                NetworkCheckRendezvousManager(
                    RendezvousParameters(min_nodes, max_nodes,
                                         ctx.rdzv_wait_new_node_s)
                ),
        }
        self.kv_store = KVStoreService(
            keep_generations=ctx.kv_gc_keep_generations)
        self.sync_service = SyncService(expected_workers=min_nodes)
        self.elastic_ps_service = ElasticPsService()
        self.job_manager = job_manager
        # the goodput ledger classifies every rank-second of the job
        # (obs/goodput.py); fed by the servicer, persisted with the
        # control-plane state, queried over RPC by tools/goodput.py
        self.goodput_ledger = obs.GoodputLedger()
        # the fleet time-series plane (obs/tsdb.py): bounded multi-
        # resolution history of the gauges/goodput/device truth, served
        # over TimeSeriesQuery and rendered by tools/top.py; the
        # collector (built with the state backend below — its sidecar
        # lives in the state dir) samples + persists it
        self.tsdb = obs.TimeSeriesStore()
        self.tsdb_collector = None
        # planner prediction <-> measurement calibration
        # (parallel/calibration.py): stamped plans register their
        # predicted step time, worker step reports register measured,
        # learned per-axis discounts feed back into planner scoring
        from dlrover_tpu.parallel.calibration import PlanCalibration

        self.plan_calibration = PlanCalibration()
        # per-step critical-path assembly (master/steptrace.py): joins
        # worker trace records, feeds the tsdb gating series, evidences
        # CriticalPathRule, serves tools/steptrace.py + the flight embed
        from dlrover_tpu.master.steptrace import StepTraceAssembler

        self.steptrace = StepTraceAssembler(tsdb=self.tsdb)
        self.diagnosis_manager = None
        if ctx.diagnosis_enabled:
            from dlrover_tpu.master.diagnosis import DiagnosisManager

            self.diagnosis_manager = DiagnosisManager(
                self.speed_monitor,
                goodput_ledger=self.goodput_ledger,
                plan_calibration=self.plan_calibration,
                steptrace=self.steptrace)
        # the goodput-optimal fleet controller
        # (brain/fleet_controller.py): closes the diagnosis→actuation
        # loop — claims offered preemptible slices, sheds gating ones,
        # holds behind guardrails. Deliberately gated on its OWN knob,
        # not the legacy auto_scale_enabled (node-count autoscaling,
        # JobAutoScaler): the two act on different layers.
        self.capacity_provider = None
        self.fleet_controller = None
        if ctx.fleet_controller_enabled:
            from dlrover_tpu.brain.fleet_controller import (
                FleetController,
                LocalCapacityProvider,
            )

            self.capacity_provider = LocalCapacityProvider()
            self.fleet_controller = FleetController(
                ledger=self.goodput_ledger,
                speed_monitor=self.speed_monitor,
                steptrace=self.steptrace,
                plan_calibration=self.plan_calibration,
                rendezvous=training_mgr,
                diagnosis=self.diagnosis_manager,
                provider=self.capacity_provider)
        self.servicer = MasterServicer(
            task_manager=self.task_manager,
            rdzv_managers=self.rdzv_managers,
            kv_store=self.kv_store,
            speed_monitor=self.speed_monitor,
            sync_service=self.sync_service,
            elastic_ps_service=self.elastic_ps_service,
            job_manager=job_manager,
            diagnosis_manager=self.diagnosis_manager,
            goodput_ledger=self.goodput_ledger,
            tsdb=self.tsdb,
            plan_calibration=self.plan_calibration,
            steptrace=self.steptrace,
            fleet_controller=self.fleet_controller,
        )
        if self.fleet_controller is not None:
            # a shed actuates through the EXISTING slice-unit drain
            # chain (the servicer's notice-phase handler)
            self.fleet_controller.shed_sink = self._controller_shed
        if self.diagnosis_manager is not None:
            # learned-discount feedback rides the diagnosis cadence,
            # not the per-report hot path (the medians only move as
            # samples accumulate)
            self.diagnosis_manager.discount_sink = \
                self.servicer.push_axis_discounts
        self._host = host
        self._server, self.port = build_server(
            self.servicer.get_bytes, self.servicer.report_bytes,
            port=port, host=host,
        )
        self._init_coord_tier(host)
        self._stopped = threading.Event()
        self._exit_reason = ""
        self.metric_collector = None
        self.auto_scaler = None
        self._metrics_server = None
        self.metrics_port = 0
        if job_manager is None and job_args is not None:
            from dlrover_tpu.master.node.event_callback import (
                PsFailoverCallback,
                RendezvousMembershipCallback,
                TaskRescheduleCallback,
            )
            from dlrover_tpu.master.node.job_manager import create_job_manager

            manager = create_job_manager(
                job_args, master_addr=self.addr,
                speed_monitor=self.speed_monitor, cluster=cluster)
            manager.add_event_callback(
                TaskRescheduleCallback(self.task_manager))
            manager.add_event_callback(
                RendezvousMembershipCallback(
                    self.rdzv_managers, self.speed_monitor,
                    diagnosis_manager=self.diagnosis_manager))
            manager.add_event_callback(
                PsFailoverCallback(self.elastic_ps_service))
            self.job_manager = manager
            self.servicer.job_manager = manager
            self._attach_optimization(job_args, brain_addr)
        self._init_state_backend(
            state_dir if state_dir is not None else ctx.master_state_dir,
            ctx.master_snapshot_retain,
            preloaded_state=preloaded_state,
        )
        self._arm_master_chaos()

    # -- the coordination tier (master/coord_service.py) ----------------
    def _init_coord_tier(self, host: str) -> None:
        """Bind the KV/coordination tier on its own port + thread pool
        so a join/telemetry storm on the control tier can never stall a
        step's dcn/ exchange (coord_port -1 = single-tier: the main
        servicer answers everything, as it always has)."""
        from dlrover_tpu.master.coord_service import CoordServicer

        self._coord_server = None
        self.coord_port = 0
        port = Context.singleton().coord_port
        if port < 0:
            return
        self.coord_servicer = CoordServicer(
            self.kv_store,
            rdzv_manager=self.rdzv_managers[RendezvousName.TRAINING],
            speed_monitor=self.speed_monitor)
        try:
            # a full-width pool: blocked KVWaits hold threads, and the
            # tier must keep answering per-step gets through a world
            # formation's wait pile-up
            self._coord_server, self.coord_port = build_server(
                self.coord_servicer.get_bytes,
                self.coord_servicer.report_bytes,
                port=port, host=host, max_workers=64)
        except RuntimeError as e:
            logger.warning("coordination tier failed to bind: %s "
                           "(serving coordination on the main port)", e)
            self._coord_server = None
            return
        self.servicer.coord_addr = self.coord_addr

    # -- crash-consistent control-plane state --------------------------
    def _init_state_backend(self, state_dir: str, retain: int,
                            preloaded_state: Optional[tuple] = None
                            ) -> None:
        """Attach the snapshot store and, when a prior master left valid
        state behind, rebuild every manager from it BEFORE serving. The
        generation token bumps once per (re)start over one state lineage
        so reconnecting agents can tell a restarted master from a
        transient outage. ``preloaded_state`` is the hot standby's warm
        copy — promotion skips the disk read it already did.

        The hot-key mutation log is replayed OVER the snapshot: the
        dcn/ and coord/ keys deliberately do not trigger snapshots, so
        their last values live in the log (state_backend.MutationLog)."""
        self._snapshot_lock = threading.Lock()
        self._state_backend = None
        self._mutation_log = None
        self._last_snapshot_ts = 0.0
        # double-primary fencing extends to the STATE DIR: once a
        # higher-generation master owns the bootstrap file, this one
        # must stop writing snapshots + mutation-log appends into the
        # shared lineage (interleaved writers would corrupt the log and
        # let a stale later-versioned snapshot win the next restore)
        with self._snapshot_lock:
            self._fenced = False
            self._last_fence_check = 0.0
            self._snapshot_timer: Optional[threading.Timer] = None
        self.generation = 0
        if state_dir:
            self._state_backend = MasterStateBackend(state_dir,
                                                     retain=retain)
            # snapshots stop the moment a higher-generation master owns
            # the lineage.  The gate reads the latched flag, NOT
            # _check_fenced: backend saves run under _snapshot_lock,
            # which _check_fenced itself acquires (the deep bootstrap
            # probe already ran at _maybe_snapshot entry, pre-lock).
            # Lock-free read is safe: _fenced only ever goes False→True
            self._state_backend.gate = (
                lambda: self._fenced)  # graftlint: disable=GL201
            self.generation = 1
            loaded = (preloaded_state if preloaded_state is not None
                      else self._state_backend.load_latest())
            if loaded is not None:
                state, version = loaded
                with obs.span("master_restore",
                              {"snapshot_version": version,
                               "preloaded": preloaded_state
                               is not None}):
                    self._restore_state(state)
                    replayed = self.kv_store.replay_mutations(
                        MutationLog.read(state_dir))
                logger.info(
                    "master state restored from snapshot v%d "
                    "(generation %d, %d hot mutations replayed)",
                    version, self.generation, replayed)
                obs.get_flight_recorder().record_event(
                    "master_restore", snapshot_version=version,
                    generation=self.generation,
                    hot_mutations_replayed=replayed)
                obs.get_registry().counter(
                    "dlrover_tpu_master_restores_total",
                    "Masters rebuilt from a state snapshot").inc()
            self._mutation_log = MutationLog(state_dir)
            # the drainer consults the fence before every write: hot-
            # only traffic (which never snapshots) must still stop the
            # moment a higher-generation master owns the lineage
            self._mutation_log.gate = self._check_fenced
            self.kv_store.attach_mutation_log(self._mutation_log)
            self.servicer.state_sink = self._maybe_snapshot
            if self._coord_server is not None:
                self.coord_servicer.state_sink = self._maybe_snapshot
            if self.diagnosis_manager is not None:
                self.diagnosis_manager.state_sink = self._maybe_snapshot
            if self.fleet_controller is not None:
                self.fleet_controller.state_sink = self._maybe_snapshot
            # the generation bump itself must be durable before the
            # first RPC is served
            self._maybe_snapshot()
        self.servicer.generation = self.generation
        # the time-series collector: samples fleet vitals into the
        # store and persists the downsampled tiers to a checksummed
        # sidecar in the state dir (deliberately NOT the snapshot
        # export — background samples must not churn save_if_changed
        # versions). A restarted master or a promoted standby sharing
        # the state dir reloads fleet history here.
        self.tsdb_collector = obs.TsdbCollector(
            self.tsdb, goodput_ledger=self.goodput_ledger,
            state_dir=state_dir or "")
        if state_dir:
            # same fence as snapshots + the mutation log: a superseded
            # primary's background flush must not clobber the promoted
            # lineage's history sidecar
            self.tsdb_collector.gate = self._check_fenced
        restored_series = self.tsdb_collector.restore()
        if restored_series:
            logger.info("fleet time-series history restored: %d "
                        "series", restored_series)
            obs.get_flight_recorder().record_event(
                "tsdb_restored", series=restored_series,
                generation=self.generation)

    def _export_state(self) -> dict:
        state = {
            "generation": self.generation,
            "rendezvous": {name: mgr.export_state()
                           for name, mgr in self.rdzv_managers.items()},
            "task_manager": self.task_manager.export_state(),
            "kv_store": self.kv_store.export_state(),
            "speed_monitor": self.speed_monitor.export_state(),
            "goodput": self.goodput_ledger.export_state(),
            "plan_calibration": self.plan_calibration.export_state(),
        }
        if self.diagnosis_manager is not None:
            state["diagnosis"] = self.diagnosis_manager.export_state()
        if self.fleet_controller is not None:
            state["fleet_controller"] = \
                self.fleet_controller.export_state()
        if self.job_manager is not None and \
                hasattr(self.job_manager, "export_state"):
            state["job_manager"] = self.job_manager.export_state()
        return state

    def _restore_state(self, state: dict) -> None:
        self.generation = int(state.get("generation", 0)) + 1
        for name, rdzv_state in state.get("rendezvous", {}).items():
            mgr = self.rdzv_managers.get(name)
            if mgr is not None:
                mgr.restore_state(rdzv_state)
        # re-fan the restored rank→slice registry to every slice-labeled
        # consumer NOW (speed monitor, diagnosis, goodput): joins are the
        # only other push site, and reconnecting agents whose worlds are
        # intact never re-join — without this, per-slice gauges and
        # eviction-by-slice would mislabel until the next real join
        training = self.rdzv_managers.get(RendezvousName.TRAINING)
        if training is not None and training.slice_map:
            self.servicer._push_slice_map(training)
        self.task_manager.restore_state(state.get("task_manager", {}))
        self.kv_store.restore_state(state.get("kv_store", {}))
        self.speed_monitor.restore_state(state.get("speed_monitor", {}))
        if "goodput" in state:
            self.goodput_ledger.restore_state(state["goodput"])
        if "plan_calibration" in state:
            self.plan_calibration.restore_state(
                state["plan_calibration"])
            # re-arm the planner with the restored evidence's learned
            # discounts NOW — waiting for the first post-failover step
            # report would score the re-formation plan with the bare
            # prior the calibration already corrected
            discounts = self.plan_calibration.axis_discounts()
            if discounts:
                self.servicer.push_axis_discounts(discounts)
        if self.diagnosis_manager is not None and "diagnosis" in state:
            self.diagnosis_manager.restore_state(state["diagnosis"])
        if self.fleet_controller is not None and \
                "fleet_controller" in state:
            # a promoted standby inherits decision history, cooldowns,
            # quarantines and any open rollback watch — the guardrails
            # must survive failover
            self.fleet_controller.restore_state(
                state["fleet_controller"])
        if self.job_manager is not None and "job_manager" in state and \
                hasattr(self.job_manager, "restore_state"):
            self.job_manager.restore_state(state["job_manager"])

    def _maybe_snapshot(self, force: bool = False) -> None:
        """Persist the control-plane state if it changed (the servicer's
        post-mutation hook). Serialized: concurrent RPC handlers must
        not interleave exports with version assignment.

        master_snapshot_min_interval_s > 0 coalesces bursts (e.g. a
        worker fleet draining a many-shard dataset would otherwise pay
        one full export+fsync per dispatch): at most one snapshot per
        interval, trading up to that much durability lag on a crash.
        A skipped mutation arms a trailing timer so the lag is bounded
        by the interval even when no later mutation ever arrives (the
        last TaskResult of a dataset must not stay doing-only forever).
        The default (0) is strict write-through."""
        if self._state_backend is None:
            return
        if self._check_fenced():
            return
        interval = Context.singleton().master_snapshot_min_interval_s
        with self._snapshot_lock:
            remaining = self._last_snapshot_ts + interval - time.time()
            if not force and interval > 0 and remaining > 0:
                if self._snapshot_timer is None:
                    timer = threading.Timer(remaining,
                                            self._trailing_snapshot)
                    timer.daemon = True
                    self._snapshot_timer = timer
                    timer.start()
                return
            # sample the mutation-log fence BEFORE exporting: every
            # mutation the export can contain already holds a smaller
            # seq (appends ride the same kv lock), so rotation keeps
            # anything newer — a hot set landing between export and
            # rotate stays durable in the rewritten log
            fence = (self._mutation_log.current_seq()
                     if self._mutation_log is not None else 0)
            try:
                written = self._state_backend.save_if_changed(
                    self._export_state())
            except Exception:  # noqa: BLE001 — durability is best-effort
                logger.exception("master state snapshot failed")
                return
            if written is not None:
                self._last_snapshot_ts = time.time()
                if self._mutation_log is not None:
                    # the snapshot's kv export includes every hot
                    # mutation below the fence: those are durable now
                    self._mutation_log.rotate(up_to_seq=fence)

    def _trailing_snapshot(self) -> None:
        """Timer body: flush the mutation that fell inside the
        coalescing window."""
        with self._snapshot_lock:
            self._snapshot_timer = None
        self._maybe_snapshot(force=True)

    @staticmethod
    def _bootstrap_file_generation() -> int:
        """The generation the bootstrap file currently carries (-1 =
        no file / pre-JSON / unreadable). One parser for the whole
        contract: the same ``resolve_bootstrap`` agents re-resolve
        through (env override included)."""
        from dlrover_tpu.agent.master_client import MasterClient

        try:
            return int(MasterClient.resolve_bootstrap().get(
                "generation", -1))
        except (TypeError, ValueError):
            return -1

    def _check_fenced(self, throttle_s: float = 2.0) -> bool:
        """Has a higher-generation master taken over the lineage? Read
        the bootstrap file at most once per ``throttle_s``; on the
        first detection, STOP this master's state writes for good —
        snapshots AND hot-key mutation-log appends — so the promoted
        primary's lineage can never be clobbered by a stale writer
        (e.g. a network-blip promotion while this one is still
        alive)."""
        with self._snapshot_lock:
            if self._fenced:
                return True
            now = time.time()
            if now - self._last_fence_check < throttle_s:
                return False
            self._last_fence_check = now
        file_gen = self._bootstrap_file_generation()
        if not self.generation or file_gen <= self.generation:
            return False
        self._mark_fenced(file_gen)
        return True

    def _mark_fenced(self, file_generation: int) -> None:
        with self._snapshot_lock:
            if self._fenced:
                return
            self._fenced = True
        # stop NEW appends; already-queued entries are discarded by the
        # drainer's gate (this method may BE on the drainer thread via
        # that gate, so closing the log here would self-join)
        self.kv_store.attach_mutation_log(None)
        logger.critical(
            "FENCED: generation %d owns the bootstrap file (ours is "
            "%d) — another master promoted past us; stopping every "
            "state write into the shared lineage", file_generation,
            self.generation)
        obs.get_flight_recorder().record_event(
            "master_fenced", file_generation=file_generation,
            our_generation=self.generation)
        obs.get_registry().counter(
            "dlrover_tpu_master_fenced_total",
            "Bootstrap publishes refused because a higher-generation "
            "master already owns the file").inc()

    def _arm_master_chaos(self) -> None:
        """kill:master:0@step — fed from worker GlobalStepReports so a
        chaos run can assassinate the control plane at a chosen step —
        plus the shard-scoped faults: kill:shard:S@step restarts slice
        S's rendezvous shard from its state partition, hang:shard:S@step
        wedges it (every other shard provably keeps serving)."""
        from dlrover_tpu.diagnostics.chaos import ChaosInjector

        chaos = ChaosInjector(role=NodeType.MASTER, rank=0)
        training = self.rdzv_managers[RendezvousName.TRAINING]
        if hasattr(training, "restart_shard"):
            chaos.shard_kill_fn = training.restart_shard
            chaos.shard_wedge_fn = training.wedge_shard
        if self.capacity_provider is not None:
            # the preemptible-market faults (offer:slice:+k@step,
            # revoke:slice:S@step) feed the local capacity provider —
            # the fleet controller's spot market in-process
            chaos.offer_fn = self.capacity_provider.offer
            chaos.revoke_fn = self.capacity_provider.revoke
        if chaos.faults:
            self.servicer.master_chaos = chaos

    def _controller_shed(self, rank: int, deadline: float,
                         reason: str) -> None:
        """Fleet-controller shed actuator: a synthetic advance-notice
        drain through the servicer's EXISTING slice-unit chain. The
        notice rank itself also gets a save-and-exit drain action — in
        a real preemption the OS notice file drives its exit, but a
        controller-initiated shed has no notice file, so the action
        queue carries the order instead."""
        from dlrover_tpu.common import messages as msg

        if self.diagnosis_manager is not None:
            self.diagnosis_manager.request_drain(
                [rank], deadline, reason=reason)
        self.servicer._handle_drain(msg.DrainReport(
            node_rank=rank, phase="notice", deadline=deadline,
            reason=reason))

    def _attach_optimization(self, job_args, brain_addr: str) -> None:
        """Wire stats collection + resource optimization + auto-scaling
        (reference: dist_master.py:116-127 reporter selection and the
        JobResourceOptimizer/JobAutoScaler composition)."""
        from dlrover_tpu.common.constants import OptimizeMode
        from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler
        from dlrover_tpu.master.stats.job_collector import JobMetricCollector
        from dlrover_tpu.master.stats.reporter import (
            ReporterType,
            StatsReporter,
        )

        use_brain = (job_args.optimize_mode == OptimizeMode.CLUSTER
                     and brain_addr)
        if use_brain:
            from dlrover_tpu.brain.client import BrainResourceOptimizer

            reporter = StatsReporter.new_reporter(
                ReporterType.BRAIN, addr=brain_addr,
                job_name=job_args.job_name, job_uuid=job_args.job_uuid)
            optimizer = BrainResourceOptimizer(brain_addr,
                                               job_args.job_name)
        else:
            from dlrover_tpu.master.resource.local_optimizer import (
                LocalResourceOptimizer,
            )

            reporter = StatsReporter.new_reporter(ReporterType.LOCAL)
            optimizer = LocalResourceOptimizer()
        self.metric_collector = JobMetricCollector(
            job_args.job_name, reporter, stats=optimizer.stats)
        self.metric_collector.attach(speed_monitor=self.speed_monitor,
                                     job_manager=self.job_manager)
        self.servicer.metric_collector = self.metric_collector
        worker_args = job_args.worker_args()
        if worker_args is not None:
            resource = worker_args.group_resource.node_resource
            self.metric_collector.report_job_meta(
                worker_count=worker_args.group_resource.count,
                cpu=resource.cpu, memory_mb=resource.memory_mb,
                chips=resource.chips, chip_type=resource.chip_type,
                distribution_strategy=job_args.distribution_strategy,
            )
        if job_args.optimize_mode != OptimizeMode.MANUAL:
            self.auto_scaler = JobAutoScaler(
                self.job_manager, optimizer,
                speed_monitor=self.speed_monitor,
                interval_s=Context.singleton().seconds_per_scale_check,
            )
            self.auto_scaler.paral_config_sink = (
                self.servicer.merge_paral_config)

    # ------------------------------------------------------------------
    def prepare(self) -> None:
        self._server.start()
        if self._coord_server is not None:
            self._coord_server.start()
            logger.info("coordination tier serving on port %d",
                        self.coord_port)
        if self.job_manager is not None:
            self.job_manager.start()
        if self.metric_collector is not None:
            self.metric_collector.start()
        if self.auto_scaler is not None:
            self.auto_scaler.start()
        self.task_manager.start_timeout_recovery()
        if self.diagnosis_manager is not None:
            self.diagnosis_manager.start()
        if self.fleet_controller is not None:
            self.fleet_controller.start()
        if self.tsdb_collector is not None:
            self.tsdb_collector.start()
        self._start_metrics_exporter()
        self._publish_bootstrap_addr()
        # an unhandled master crash still leaves the job timeline on disk
        obs.get_flight_recorder().install_excepthook()
        logger.info("job master serving on port %d", self.port)

    def _publish_bootstrap_addr(self) -> None:
        """Atomically write the advertised addresses + generation token
        to the bootstrap file (JSON since the hot-standby work; plain
        pre-JSON files are still read by resolve_bootstrap) so agents in
        master-lost mode can re-resolve a restarted OR promoted master.

        Generation fencing: a file already carrying a HIGHER generation
        is never overwritten — a revived old primary coming back after a
        standby promoted must not steal the fleet back (double-primary
        split-brain). The fenced master logs CRITICAL and keeps serving
        whoever still dials its old address; agents re-resolve to the
        higher generation."""
        import json

        path = Context.singleton().master_bootstrap_file
        if not path:
            return
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            # the read-check-replace must be one critical section: two
            # masters racing it bare could interleave so the LOWER
            # generation's replace lands last and permanently points
            # the fleet at the stale primary. Advisory flock on a
            # sidecar serializes every publisher using this code.
            with self._bootstrap_publish_lock(path):
                current_gen = self._bootstrap_file_generation()
                if self.generation and current_gen > self.generation:
                    # fencing covers the whole lineage, not just the
                    # file: this master also stops snapshot/mutation-
                    # log writes
                    self._mark_fenced(current_gen)
                    return
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "w") as f:
                    json.dump({"addr": self.addr,
                               "coord_addr": self.coord_addr,
                               "generation": self.generation}, f)
                os.replace(tmp, path)
        except OSError as e:
            logger.warning("cannot publish master address to %s: %s",
                           path, e)
            return
        logger.info("master address %s (coord %s, generation %d) "
                    "published to %s", self.addr,
                    self.coord_addr or "-", self.generation, path)

    @staticmethod
    def _bootstrap_publish_lock(path: str):
        """Advisory exclusive lock over the bootstrap publish critical
        section (best-effort: a filesystem without flock degrades to
        the bare race, which is still bounded by the fence check)."""
        import contextlib

        @contextlib.contextmanager
        def held():
            lock_file = None
            try:
                import fcntl

                lock_file = open(f"{path}.lock", "w")
                fcntl.flock(lock_file, fcntl.LOCK_EX)
            except (ImportError, OSError):
                # acquisition failure only — a body exception must
                # never land here (it would make the manager re-yield)
                if lock_file is not None:
                    lock_file.close()
                lock_file = None
            try:
                yield
            finally:
                if lock_file is not None:
                    try:
                        fcntl.flock(lock_file, fcntl.LOCK_UN)
                        lock_file.close()
                    except OSError:
                        pass
        return held()

    def _start_metrics_exporter(self) -> None:
        """Serve the Prometheus exposition (metrics_port: 0 = any free
        port, negative = disabled). Scrape: GET /metrics — see
        docs/observability.md."""
        port = Context.singleton().metrics_port
        if port < 0:
            return
        try:
            # bound during prepare(), before run_in_thread() spawns:
            # the run thread only reads it at shutdown
            self._metrics_server, self.metrics_port = (  # graftlint: disable=GL701
                obs.start_http_exporter(port=port))
        except OSError as e:
            logger.warning("metrics exporter failed to bind: %s", e)
            return
        logger.info("metrics exposition on :%d/metrics", self.metrics_port)

    def run(self, poll_interval_s: float = 30.0) -> int:
        """Block until the job finishes; returns an exit code (reference:
        dist_master.py:165-222)."""
        ctx = Context.singleton()
        exit_code = 0
        while not self._stopped.is_set():
            if self.job_manager is not None:
                stage = self.job_manager.job_stage()
                if stage == JobStage.SUCCEEDED:
                    break
                if stage == JobStage.FAILED:
                    exit_code = 1
                    # single writer (this loop); read after run() exits
                    self._exit_reason = self.job_manager.exit_reason()  # graftlint: disable=GL701
                    break
            elif self.task_manager.finished():
                logger.info("all datasets exhausted: job succeeded")
                break
            if self.speed_monitor.is_hanged(ctx.hang_seconds):
                logger.error("job hanged > %.0fs without step progress",
                             ctx.hang_seconds)
                exit_code = 1
                # single writer (this loop); read after run() exits
                self._exit_reason = "hang"  # graftlint: disable=GL701
                break
            self._stopped.wait(poll_interval_s)
        self.stop()
        return exit_code

    def run_in_thread(self, poll_interval_s: float = 1.0) -> threading.Thread:
        thread = threading.Thread(
            target=self.run, args=(poll_interval_s,), daemon=True,
            name="job-master",
        )
        thread.start()
        return thread

    def stop(self, grace_s: float = 1.0) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            if self.metric_collector is not None:
                stage = (self.job_manager.job_stage()
                         if self.job_manager else "")
                self.metric_collector.report_job_exit(stage,
                                                      self._exit_reason)
                self.metric_collector.stop()
            if self.auto_scaler is not None:
                self.auto_scaler.stop()
            if self.diagnosis_manager is not None:
                self.diagnosis_manager.stop()
            if self.fleet_controller is not None:
                self.fleet_controller.stop()
                try:
                    # the decision history rides in the dump so
                    # `tools/diagnose.py --flight` renders the exact
                    # payload the live RPC served
                    obs.get_flight_recorder().record_event(
                        "autoscale",
                        status=self.fleet_controller.status())
                except Exception:  # noqa: BLE001 — the dump must land
                    logger.exception("autoscale flight snapshot failed")
            if self.job_manager is not None:
                self.job_manager.stop()
            if self._metrics_server is not None:
                self._metrics_server.shutdown()
                self._metrics_server.server_close()  # release the socket
            with self._snapshot_lock:
                if self._snapshot_timer is not None:
                    self._snapshot_timer.cancel()
                    self._snapshot_timer = None
            # queued telemetry is replayed before the final flight dump
            # (a graceful stop must not silently drop spans), then the
            # drainer stops
            self.servicer.telemetry_queue.flush(timeout_s=2.0)
            self.servicer.telemetry_queue.stop()
            # a coalesced mutation must not die with the process when
            # the stop is graceful
            self._maybe_snapshot(force=True)
            if self._mutation_log is not None:
                self._mutation_log.close()
            # the master's half of the postmortem timeline; the goodput
            # snapshot rides in the dump so `tools/goodput.py --flight`
            # renders the ledger from the postmortem alone
            self.goodput_ledger.record_flight_snapshot(
                reason="master-stop")
            if self.tsdb_collector is not None:
                # final history flush + a compact tsdb snapshot in the
                # dump so `tools/top.py --flight` renders sparklines
                # from the postmortem alone
                self.tsdb_collector.stop()
                try:
                    obs.get_flight_recorder().record_event(
                        "tsdb",
                        snapshot=self.tsdb_collector.flight_snapshot(),
                        calibration=self.plan_calibration.table(),
                        axis_discounts=self.plan_calibration
                        .axis_discounts())
                except Exception:  # noqa: BLE001 — the dump must land
                    logger.exception("tsdb flight snapshot failed")
            try:
                # the assembled waterfall rides in the dump so
                # `tools/steptrace.py --flight` renders the exact
                # payload the live RPC served
                obs.get_flight_recorder().record_event(
                    "steptrace",
                    snapshot=self.steptrace.flight_snapshot())
            except Exception:  # noqa: BLE001 — the dump must land
                logger.exception("steptrace flight snapshot failed")
            obs.get_flight_recorder().record_event(
                "master_stop", exit_reason=self._exit_reason)
            obs.get_flight_recorder().dump(reason="master-stop")
            if self._coord_server is not None:
                self._coord_server.stop(grace_s)
            self._server.stop(grace_s)

    @property
    def addr(self) -> str:
        """Address agents should dial. A 0.0.0.0 bind is advertised as the
        host's routable IP so multi-host agents don't dial their own
        loopback."""
        return f"{self._advertised_host()}:{self.port}"

    @property
    def coord_addr(self) -> str:
        """The coordination tier's advertised address ("" = single-tier:
        coordination served on the main port)."""
        if self._coord_server is None:
            return ""
        return f"{self._advertised_host()}:{self.coord_port}"

    def _advertised_host(self) -> str:
        from dlrover_tpu.common.comm import local_ip

        host = self._host
        if host in ("0.0.0.0", "::", ""):
            host = local_ip()
        return host


def run_master_main(args=None) -> int:
    """CLI entry: `python -m dlrover_tpu.master.job_master --port ...`
    (reference: master/main.py:55, platform dispatch main.py:37-52).

    On `--platform k8s` the master fetches its own ElasticJob CR (the
    operator only passes the job name — reference: the Go master pod gets
    the job name and reads the CRD) and runs the full node-lifecycle
    composition with the pod scaler/watcher; otherwise it is the
    standalone/local rendezvous master."""
    import argparse

    parser = argparse.ArgumentParser("dlrover-tpu master")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--min-nodes", type=int, default=1)
    parser.add_argument("--max-nodes", type=int, default=1)
    parser.add_argument("--node-unit", type=int, default=1)
    parser.add_argument("--platform", default="local",
                        choices=["local", "k8s"])
    parser.add_argument("--job-name", default="")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--brain-addr", default="")
    parser.add_argument("--metrics-port", type=int,
                        default=Context.singleton().metrics_port,
                        help="Prometheus /metrics port (0 = any free "
                             "port, -1 = disabled)")
    parser.add_argument("--state-dir",
                        default=Context.singleton().master_state_dir,
                        help="directory for crash-consistent control-"
                             "plane snapshots; a restarted master "
                             "recovers from the latest valid one "
                             "('' = disabled)")
    parser.add_argument("--bootstrap-file",
                        default=Context.singleton().master_bootstrap_file,
                        help="file the master atomically writes its "
                             "advertised address into; agents re-resolve "
                             "from it after a master restart")
    parser.add_argument("--standby", action="store_true",
                        help="run as a HOT STANDBY instead of the "
                             "primary: tail the primary's snapshot "
                             "stream under --state-dir, health-check "
                             "the address it publishes in "
                             "--bootstrap-file, and promote (serve from "
                             "warm state, bumped generation, no worker "
                             "restarts) when it stops answering")
    ns = parser.parse_args(args)
    Context.singleton().update(metrics_port=ns.metrics_port,
                               master_state_dir=ns.state_dir,
                               master_bootstrap_file=ns.bootstrap_file)
    if ns.standby:
        from dlrover_tpu.master.standby import StandbyMaster

        standby = StandbyMaster(
            state_dir=ns.state_dir, bootstrap_file=ns.bootstrap_file,
            port=ns.port, min_nodes=ns.min_nodes,
            max_nodes=ns.max_nodes, node_unit=ns.node_unit)
        print("DLROVER_TPU_STANDBY=watching", flush=True)
        return standby.run()
    if ns.platform == "k8s":
        from dlrover_tpu.operator.crd import (
            ELASTICJOB_PLURAL,
            GROUP,
            VERSION,
            ElasticJob,
        )
        from dlrover_tpu.scheduler.kubernetes import K8sClient

        client = K8sClient(namespace=ns.namespace)
        manifest = client.api.request(
            "GET",
            f"/apis/{GROUP}/{VERSION}/namespaces/{ns.namespace}"
            f"/{ELASTICJOB_PLURAL}/{ns.job_name}")
        job = ElasticJob.from_manifest(manifest)
        job_args = job.to_job_args()
        worker = job_args.worker_args()
        if worker is not None:
            count = worker.group_resource.count
            min_nodes = max(1, worker.min_count or count)
            max_nodes = max(min_nodes, worker.max_count or count)
        else:
            min_nodes = max_nodes = 1
        master = JobMaster(port=ns.port, min_nodes=min_nodes,
                           max_nodes=max_nodes, node_unit=ns.node_unit,
                           job_args=job_args, cluster=client,
                           brain_addr=ns.brain_addr)
    else:
        master = JobMaster(port=ns.port, min_nodes=ns.min_nodes,
                           max_nodes=ns.max_nodes, node_unit=ns.node_unit)
    master.prepare()
    print(f"DLROVER_TPU_MASTER_ADDR={master.addr}", flush=True)
    return master.run()


if __name__ == "__main__":
    raise SystemExit(run_master_main())
