"""Job master: composition root + serving loop.

Capability parity: dlrover/python/master/local_master.py:38 (LocalJobMaster)
and dist_master.py:53 (DistributedJobMaster composition :62-71, 30 s watch
loop :165-222). The master owns every control-plane component and runs the
gRPC service; `prepare()` starts serving, `run()` polls for job completion /
hang; the node manager (when attached) owns node lifecycle.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from dlrover_tpu import obs
from dlrover_tpu.common.comm import build_server
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import JobStage, NodeType, RendezvousName
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.state_backend import MasterStateBackend
from dlrover_tpu.master.rendezvous import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousParameters,
)
from dlrover_tpu.master.servicer import MasterServicer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.sync_service import ElasticPsService, SyncService


class JobMaster:
    """One instance per job. With no node manager attached this is the
    standalone/local master (the `dlrover-run --standalone` equivalent)."""

    def __init__(
        self,
        port: int = 0,
        min_nodes: int = 1,
        max_nodes: int = 1,
        node_unit: int = 1,
        job_manager=None,
        job_args=None,
        cluster=None,
        host: str = "0.0.0.0",
        brain_addr: str = "",
        state_dir: Optional[str] = None,
    ):
        ctx = Context.singleton()
        params = RendezvousParameters(
            min_nodes=min_nodes,
            max_nodes=max_nodes,
            wait_new_node_s=ctx.rdzv_wait_new_node_s,
            node_unit=node_unit,
        )
        self.task_manager = TaskManager()
        self.speed_monitor = SpeedMonitor()
        self.task_manager.speed_monitor = self.speed_monitor
        self.rdzv_managers = {
            RendezvousName.TRAINING:
                ElasticTrainingRendezvousManager(params),
            RendezvousName.NETWORK_CHECK:
                NetworkCheckRendezvousManager(
                    RendezvousParameters(min_nodes, max_nodes,
                                         ctx.rdzv_wait_new_node_s)
                ),
        }
        self.kv_store = KVStoreService()
        self.sync_service = SyncService(expected_workers=min_nodes)
        self.elastic_ps_service = ElasticPsService()
        self.job_manager = job_manager
        # the goodput ledger classifies every rank-second of the job
        # (obs/goodput.py); fed by the servicer, persisted with the
        # control-plane state, queried over RPC by tools/goodput.py
        self.goodput_ledger = obs.GoodputLedger()
        self.diagnosis_manager = None
        if ctx.diagnosis_enabled:
            from dlrover_tpu.master.diagnosis import DiagnosisManager

            self.diagnosis_manager = DiagnosisManager(
                self.speed_monitor,
                goodput_ledger=self.goodput_ledger)
        self.servicer = MasterServicer(
            task_manager=self.task_manager,
            rdzv_managers=self.rdzv_managers,
            kv_store=self.kv_store,
            speed_monitor=self.speed_monitor,
            sync_service=self.sync_service,
            elastic_ps_service=self.elastic_ps_service,
            job_manager=job_manager,
            diagnosis_manager=self.diagnosis_manager,
            goodput_ledger=self.goodput_ledger,
        )
        self._host = host
        self._server, self.port = build_server(
            self.servicer.get_bytes, self.servicer.report_bytes,
            port=port, host=host,
        )
        self._stopped = threading.Event()
        self._exit_reason = ""
        self.metric_collector = None
        self.auto_scaler = None
        self._metrics_server = None
        self.metrics_port = 0
        if job_manager is None and job_args is not None:
            from dlrover_tpu.master.node.event_callback import (
                PsFailoverCallback,
                RendezvousMembershipCallback,
                TaskRescheduleCallback,
            )
            from dlrover_tpu.master.node.job_manager import create_job_manager

            manager = create_job_manager(
                job_args, master_addr=self.addr,
                speed_monitor=self.speed_monitor, cluster=cluster)
            manager.add_event_callback(
                TaskRescheduleCallback(self.task_manager))
            manager.add_event_callback(
                RendezvousMembershipCallback(
                    self.rdzv_managers, self.speed_monitor,
                    diagnosis_manager=self.diagnosis_manager))
            manager.add_event_callback(
                PsFailoverCallback(self.elastic_ps_service))
            self.job_manager = manager
            self.servicer.job_manager = manager
            self._attach_optimization(job_args, brain_addr)
        self._init_state_backend(
            state_dir if state_dir is not None else ctx.master_state_dir,
            ctx.master_snapshot_retain,
        )
        self._arm_master_chaos()

    # -- crash-consistent control-plane state --------------------------
    def _init_state_backend(self, state_dir: str, retain: int) -> None:
        """Attach the snapshot store and, when a prior master left valid
        state behind, rebuild every manager from it BEFORE serving. The
        generation token bumps once per (re)start over one state lineage
        so reconnecting agents can tell a restarted master from a
        transient outage."""
        self._snapshot_lock = threading.Lock()
        self._state_backend = None
        self._last_snapshot_ts = 0.0
        with self._snapshot_lock:
            self._snapshot_timer: Optional[threading.Timer] = None
        self.generation = 0
        if state_dir:
            self._state_backend = MasterStateBackend(state_dir,
                                                     retain=retain)
            self.generation = 1
            loaded = self._state_backend.load_latest()
            if loaded is not None:
                state, version = loaded
                with obs.span("master_restore",
                              {"snapshot_version": version}):
                    self._restore_state(state)
                logger.info(
                    "master state restored from snapshot v%d "
                    "(generation %d)", version, self.generation)
                obs.get_flight_recorder().record_event(
                    "master_restore", snapshot_version=version,
                    generation=self.generation)
                obs.get_registry().counter(
                    "dlrover_tpu_master_restores_total",
                    "Masters rebuilt from a state snapshot").inc()
            self.servicer.state_sink = self._maybe_snapshot
            if self.diagnosis_manager is not None:
                self.diagnosis_manager.state_sink = self._maybe_snapshot
            # the generation bump itself must be durable before the
            # first RPC is served
            self._maybe_snapshot()
        self.servicer.generation = self.generation

    def _export_state(self) -> dict:
        state = {
            "generation": self.generation,
            "rendezvous": {name: mgr.export_state()
                           for name, mgr in self.rdzv_managers.items()},
            "task_manager": self.task_manager.export_state(),
            "kv_store": self.kv_store.export_state(),
            "speed_monitor": self.speed_monitor.export_state(),
            "goodput": self.goodput_ledger.export_state(),
        }
        if self.diagnosis_manager is not None:
            state["diagnosis"] = self.diagnosis_manager.export_state()
        if self.job_manager is not None and \
                hasattr(self.job_manager, "export_state"):
            state["job_manager"] = self.job_manager.export_state()
        return state

    def _restore_state(self, state: dict) -> None:
        self.generation = int(state.get("generation", 0)) + 1
        for name, rdzv_state in state.get("rendezvous", {}).items():
            mgr = self.rdzv_managers.get(name)
            if mgr is not None:
                mgr.restore_state(rdzv_state)
        self.task_manager.restore_state(state.get("task_manager", {}))
        self.kv_store.restore_state(state.get("kv_store", {}))
        self.speed_monitor.restore_state(state.get("speed_monitor", {}))
        if "goodput" in state:
            self.goodput_ledger.restore_state(state["goodput"])
        if self.diagnosis_manager is not None and "diagnosis" in state:
            self.diagnosis_manager.restore_state(state["diagnosis"])
        if self.job_manager is not None and "job_manager" in state and \
                hasattr(self.job_manager, "restore_state"):
            self.job_manager.restore_state(state["job_manager"])

    def _maybe_snapshot(self, force: bool = False) -> None:
        """Persist the control-plane state if it changed (the servicer's
        post-mutation hook). Serialized: concurrent RPC handlers must
        not interleave exports with version assignment.

        master_snapshot_min_interval_s > 0 coalesces bursts (e.g. a
        worker fleet draining a many-shard dataset would otherwise pay
        one full export+fsync per dispatch): at most one snapshot per
        interval, trading up to that much durability lag on a crash.
        A skipped mutation arms a trailing timer so the lag is bounded
        by the interval even when no later mutation ever arrives (the
        last TaskResult of a dataset must not stay doing-only forever).
        The default (0) is strict write-through."""
        if self._state_backend is None:
            return
        interval = Context.singleton().master_snapshot_min_interval_s
        with self._snapshot_lock:
            remaining = self._last_snapshot_ts + interval - time.time()
            if not force and interval > 0 and remaining > 0:
                if self._snapshot_timer is None:
                    timer = threading.Timer(remaining,
                                            self._trailing_snapshot)
                    timer.daemon = True
                    self._snapshot_timer = timer
                    timer.start()
                return
            try:
                written = self._state_backend.save_if_changed(
                    self._export_state())
            except Exception:  # noqa: BLE001 — durability is best-effort
                logger.exception("master state snapshot failed")
                return
            if written is not None:
                self._last_snapshot_ts = time.time()

    def _trailing_snapshot(self) -> None:
        """Timer body: flush the mutation that fell inside the
        coalescing window."""
        with self._snapshot_lock:
            self._snapshot_timer = None
        self._maybe_snapshot(force=True)

    def _arm_master_chaos(self) -> None:
        """kill:master:0@step — fed from worker GlobalStepReports so a
        chaos run can assassinate the control plane at a chosen step."""
        from dlrover_tpu.diagnostics.chaos import ChaosInjector

        chaos = ChaosInjector(role=NodeType.MASTER, rank=0)
        if chaos.faults:
            self.servicer.master_chaos = chaos

    def _attach_optimization(self, job_args, brain_addr: str) -> None:
        """Wire stats collection + resource optimization + auto-scaling
        (reference: dist_master.py:116-127 reporter selection and the
        JobResourceOptimizer/JobAutoScaler composition)."""
        from dlrover_tpu.common.constants import OptimizeMode
        from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler
        from dlrover_tpu.master.stats.job_collector import JobMetricCollector
        from dlrover_tpu.master.stats.reporter import (
            ReporterType,
            StatsReporter,
        )

        use_brain = (job_args.optimize_mode == OptimizeMode.CLUSTER
                     and brain_addr)
        if use_brain:
            from dlrover_tpu.brain.client import BrainResourceOptimizer

            reporter = StatsReporter.new_reporter(
                ReporterType.BRAIN, addr=brain_addr,
                job_name=job_args.job_name, job_uuid=job_args.job_uuid)
            optimizer = BrainResourceOptimizer(brain_addr,
                                               job_args.job_name)
        else:
            from dlrover_tpu.master.resource.local_optimizer import (
                LocalResourceOptimizer,
            )

            reporter = StatsReporter.new_reporter(ReporterType.LOCAL)
            optimizer = LocalResourceOptimizer()
        self.metric_collector = JobMetricCollector(
            job_args.job_name, reporter, stats=optimizer.stats)
        self.metric_collector.attach(speed_monitor=self.speed_monitor,
                                     job_manager=self.job_manager)
        self.servicer.metric_collector = self.metric_collector
        worker_args = job_args.worker_args()
        if worker_args is not None:
            resource = worker_args.group_resource.node_resource
            self.metric_collector.report_job_meta(
                worker_count=worker_args.group_resource.count,
                cpu=resource.cpu, memory_mb=resource.memory_mb,
                chips=resource.chips, chip_type=resource.chip_type,
                distribution_strategy=job_args.distribution_strategy,
            )
        if job_args.optimize_mode != OptimizeMode.MANUAL:
            self.auto_scaler = JobAutoScaler(
                self.job_manager, optimizer,
                speed_monitor=self.speed_monitor,
                interval_s=Context.singleton().seconds_per_scale_check,
            )
            self.auto_scaler.paral_config_sink = (
                self.servicer.merge_paral_config)

    # ------------------------------------------------------------------
    def prepare(self) -> None:
        self._server.start()
        if self.job_manager is not None:
            self.job_manager.start()
        if self.metric_collector is not None:
            self.metric_collector.start()
        if self.auto_scaler is not None:
            self.auto_scaler.start()
        self.task_manager.start_timeout_recovery()
        if self.diagnosis_manager is not None:
            self.diagnosis_manager.start()
        self._start_metrics_exporter()
        self._publish_bootstrap_addr()
        # an unhandled master crash still leaves the job timeline on disk
        obs.get_flight_recorder().install_excepthook()
        logger.info("job master serving on port %d", self.port)

    def _publish_bootstrap_addr(self) -> None:
        """Atomically write the advertised address to the bootstrap file
        so agents in master-lost mode can re-resolve a restarted master
        (whose port/IP usually changed)."""
        path = Context.singleton().master_bootstrap_file
        if not path:
            return
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = f"{path}.{os.getpid()}.tmp"
            with open(tmp, "w") as f:
                f.write(self.addr)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("cannot publish master address to %s: %s",
                           path, e)
            return
        logger.info("master address %s published to %s", self.addr, path)

    def _start_metrics_exporter(self) -> None:
        """Serve the Prometheus exposition (metrics_port: 0 = any free
        port, negative = disabled). Scrape: GET /metrics — see
        docs/observability.md."""
        port = Context.singleton().metrics_port
        if port < 0:
            return
        try:
            self._metrics_server, self.metrics_port = (
                obs.start_http_exporter(port=port))
        except OSError as e:
            logger.warning("metrics exporter failed to bind: %s", e)
            return
        logger.info("metrics exposition on :%d/metrics", self.metrics_port)

    def run(self, poll_interval_s: float = 30.0) -> int:
        """Block until the job finishes; returns an exit code (reference:
        dist_master.py:165-222)."""
        ctx = Context.singleton()
        exit_code = 0
        while not self._stopped.is_set():
            if self.job_manager is not None:
                stage = self.job_manager.job_stage()
                if stage == JobStage.SUCCEEDED:
                    break
                if stage == JobStage.FAILED:
                    exit_code = 1
                    self._exit_reason = self.job_manager.exit_reason()
                    break
            elif self.task_manager.finished():
                logger.info("all datasets exhausted: job succeeded")
                break
            if self.speed_monitor.is_hanged(ctx.hang_seconds):
                logger.error("job hanged > %.0fs without step progress",
                             ctx.hang_seconds)
                exit_code = 1
                self._exit_reason = "hang"
                break
            self._stopped.wait(poll_interval_s)
        self.stop()
        return exit_code

    def run_in_thread(self, poll_interval_s: float = 1.0) -> threading.Thread:
        thread = threading.Thread(
            target=self.run, args=(poll_interval_s,), daemon=True,
            name="job-master",
        )
        thread.start()
        return thread

    def stop(self, grace_s: float = 1.0) -> None:
        if not self._stopped.is_set():
            self._stopped.set()
            if self.metric_collector is not None:
                stage = (self.job_manager.job_stage()
                         if self.job_manager else "")
                self.metric_collector.report_job_exit(stage,
                                                      self._exit_reason)
                self.metric_collector.stop()
            if self.auto_scaler is not None:
                self.auto_scaler.stop()
            if self.diagnosis_manager is not None:
                self.diagnosis_manager.stop()
            if self.job_manager is not None:
                self.job_manager.stop()
            if self._metrics_server is not None:
                self._metrics_server.shutdown()
                self._metrics_server.server_close()  # release the socket
            with self._snapshot_lock:
                if self._snapshot_timer is not None:
                    self._snapshot_timer.cancel()
                    self._snapshot_timer = None
            # a coalesced mutation must not die with the process when
            # the stop is graceful
            self._maybe_snapshot(force=True)
            # the master's half of the postmortem timeline; the goodput
            # snapshot rides in the dump so `tools/goodput.py --flight`
            # renders the ledger from the postmortem alone
            self.goodput_ledger.record_flight_snapshot(
                reason="master-stop")
            obs.get_flight_recorder().record_event(
                "master_stop", exit_reason=self._exit_reason)
            obs.get_flight_recorder().dump(reason="master-stop")
            self._server.stop(grace_s)

    @property
    def addr(self) -> str:
        """Address agents should dial. A 0.0.0.0 bind is advertised as the
        host's routable IP so multi-host agents don't dial their own
        loopback."""
        from dlrover_tpu.common.comm import local_ip

        host = self._host
        if host in ("0.0.0.0", "::", ""):
            host = local_ip()
        return f"{host}:{self.port}"


def run_master_main(args=None) -> int:
    """CLI entry: `python -m dlrover_tpu.master.job_master --port ...`
    (reference: master/main.py:55, platform dispatch main.py:37-52).

    On `--platform k8s` the master fetches its own ElasticJob CR (the
    operator only passes the job name — reference: the Go master pod gets
    the job name and reads the CRD) and runs the full node-lifecycle
    composition with the pod scaler/watcher; otherwise it is the
    standalone/local rendezvous master."""
    import argparse

    parser = argparse.ArgumentParser("dlrover-tpu master")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--min-nodes", type=int, default=1)
    parser.add_argument("--max-nodes", type=int, default=1)
    parser.add_argument("--node-unit", type=int, default=1)
    parser.add_argument("--platform", default="local",
                        choices=["local", "k8s"])
    parser.add_argument("--job-name", default="")
    parser.add_argument("--namespace", default="default")
    parser.add_argument("--brain-addr", default="")
    parser.add_argument("--metrics-port", type=int,
                        default=Context.singleton().metrics_port,
                        help="Prometheus /metrics port (0 = any free "
                             "port, -1 = disabled)")
    parser.add_argument("--state-dir",
                        default=Context.singleton().master_state_dir,
                        help="directory for crash-consistent control-"
                             "plane snapshots; a restarted master "
                             "recovers from the latest valid one "
                             "('' = disabled)")
    parser.add_argument("--bootstrap-file",
                        default=Context.singleton().master_bootstrap_file,
                        help="file the master atomically writes its "
                             "advertised address into; agents re-resolve "
                             "from it after a master restart")
    ns = parser.parse_args(args)
    Context.singleton().update(metrics_port=ns.metrics_port,
                               master_state_dir=ns.state_dir,
                               master_bootstrap_file=ns.bootstrap_file)
    if ns.platform == "k8s":
        from dlrover_tpu.operator.crd import (
            ELASTICJOB_PLURAL,
            GROUP,
            VERSION,
            ElasticJob,
        )
        from dlrover_tpu.scheduler.kubernetes import K8sClient

        client = K8sClient(namespace=ns.namespace)
        manifest = client.api.request(
            "GET",
            f"/apis/{GROUP}/{VERSION}/namespaces/{ns.namespace}"
            f"/{ELASTICJOB_PLURAL}/{ns.job_name}")
        job = ElasticJob.from_manifest(manifest)
        job_args = job.to_job_args()
        worker = job_args.worker_args()
        if worker is not None:
            count = worker.group_resource.count
            min_nodes = max(1, worker.min_count or count)
            max_nodes = max(min_nodes, worker.max_count or count)
        else:
            min_nodes = max_nodes = 1
        master = JobMaster(port=ns.port, min_nodes=min_nodes,
                           max_nodes=max_nodes, node_unit=ns.node_unit,
                           job_args=job_args, cluster=client,
                           brain_addr=ns.brain_addr)
    else:
        master = JobMaster(port=ns.port, min_nodes=ns.min_nodes,
                           max_nodes=ns.max_nodes, node_unit=ns.node_unit)
    master.prepare()
    print(f"DLROVER_TPU_MASTER_ADDR={master.addr}", flush=True)
    return master.run()


if __name__ == "__main__":
    raise SystemExit(run_master_main())
