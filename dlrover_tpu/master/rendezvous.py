"""Master-side rendezvous managers.

Capability parity: dlrover/python/master/elastic_training/rdzv_manager.py —
min/max-node rendezvous with a waiting list and `node_unit` rounding
(`_check_rdzv_completed` rdzv_manager.py:104, `join_rendezvous` :146), plus
the 2-round network-check rendezvous with pair grouping, fault isolation and
2×median straggler verdicts (`_group_nodes` :299, `check_fault_node` :399,
`_detect_stragglers` :446).

TPU framing: a "node" is one TPU host (one JAX process); ``local_world_size``
is the host's chip count. A completed rendezvous round yields the world map
{node_rank → chips} from which agents derive ``jax.distributed`` process
count/index and the coordinator, then training re-lowers onto the new mesh.
"""

from __future__ import annotations

import statistics
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import default_logger as logger


@dataclass
class RendezvousParameters:
    min_nodes: int = 1
    max_nodes: int = 1
    # After min_nodes have joined, wait this long for late nodes up to max.
    wait_new_node_s: float = 30.0
    # World size is rounded down to a multiple of node_unit (e.g. a pipeline
    # stage count or a DCN slice granule).
    node_unit: int = 1


@dataclass
class _WaitingNode:
    node_rank: int
    local_world_size: int
    join_time: float = field(default_factory=time.time)


def plan_restore_entries(stores: Dict[int, Dict], node_rank: int,
                         slices: Dict[int, int],
                         stripe: bool = False) -> Dict:
    """The pure donor-selection core of ``compute_restore_plan``:
    ``stores`` must already be filtered to alive, non-draining donors.
    Shared by the single-lock manager (which calls it under its lock)
    and the sharded router (which calls it with aggregated copies —
    master/rendezvous_shards.py). Returns {"step", "entries", "donors"}
    (epoch stamping is the caller's)."""
    if not stores:
        return {"step": -1, "entries": {}, "donors": {}}
    step = max(store["step"] for store in stores.values())
    at_step = {rank: store for rank, store in stores.items()
               if store["step"] == step}
    requester_slice = slices.get(node_rank, -1)
    holders: Dict[str, List[int]] = {}
    for rank in sorted(at_step):
        for key in at_step[rank]["keys"]:
            holders.setdefault(key, []).append(rank)
    entries: Dict[str, Dict] = {}
    # independent round-robin cursors per tier, so the ICI tier
    # spreads across same-slice donors and the DCN tier across the
    # rest — one shared cursor would skew whichever tier the other
    # consumed from
    spread_same = 0
    spread_cross = 0
    for key in sorted(holders):
        ranks = holders[key]
        if node_rank in ranks:
            donor, tier = node_rank, "local"
        elif stripe and len(ranks) > 1:
            # resharding migration: order every holder same-slice
            # first, then the rest — the receiver stripes the shard's
            # bytes across them in parallel
            same = [r for r in ranks
                    if requester_slice >= 0
                    and slices.get(r, -1) == requester_slice]
            ordered = same + [r for r in ranks if r not in same]
            entries[key] = {
                "ranks": ordered,
                "addrs": [at_step[r]["addr"] for r in ordered],
                "tier": "striped"}
            continue
        else:
            same = [r for r in ranks
                    if requester_slice >= 0
                    and slices.get(r, -1) == requester_slice]
            if same:
                donor = same[spread_same % len(same)]
                spread_same += 1
                tier = "same-slice"
            else:
                donor = ranks[spread_cross % len(ranks)]
                spread_cross += 1
                tier = "cross-slice"
        entries[key] = {"rank": donor,
                        "addr": at_step[donor]["addr"],
                        "tier": tier}
    return {
        "step": step, "entries": entries,
        "donors": {rank: at_step[rank]["addr"] for rank in at_step},
    }


class RendezvousManager:
    """Base rendezvous: collect joiners, cut a round when complete.

    Slice-scoped mode (multi-slice hierarchical DP): when joins carry a
    slice id (and the manager class opts in via ``slice_scoped``), the
    SLICE is the failure domain — each slice cuts its own world with its
    own round counter and generation token, a member death invalidates
    only that slice's world, and the surviving slices' worlds (and
    tokens, and worker pids) are untouched. The fleet-level structures
    (_latest_world/_rdzv_round) stay idle in slice mode; the fleet view
    is the union of slice worlds."""

    name = "base"
    # slice-scoped worlds apply to training rendezvous; the 2-round
    # network-check pairing is deliberately fleet-wide (the probe WANTS
    # cross-slice pairs — DCN links are exactly what it checks)
    slice_scoped = True

    def __init__(self, params: Optional[RendezvousParameters] = None):
        # graftlint: ephemeral(re-derived via update_rdzv_params)
        self._params = params or RendezvousParameters()
        self._lock = threading.Lock()
        self._waiting: Dict[int, _WaitingNode] = {}
        self._alive_nodes: set = set()
        self._rdzv_round = 0
        self._latest_world: Dict[int, int] = {}   # node_rank -> local_world
        self._latest_round_start = 0.0
        self._node_ips: Dict[int, str] = {}
        # Survivors of an invalidated world that have not yet re-joined.
        # The membership-change signal stays raised (level-triggered) until
        # every one of them re-joins or dies — a survivor whose poll missed
        # the first window must still be told to restart.
        self._pending_rejoin: set = set()
        # rank -> last RPC touch (join / comm-world / waiting-num polls):
        # the liveness source for reap_dead_nodes in topologies with no
        # node manager (standalone/CLI masters — reference analogue: the
        # torch rendezvous backend expiring silent members,
        # elastic_agent/torch/training.py:483-521)
        self._last_seen: Dict[int, float] = {}
        # bumped on every mutation of EXPORTED state (joins, leaves,
        # round cuts, membership changes — NOT liveness touches): lets
        # the servicer skip the full state export+hash on the
        # steady-state polls, which mutate nothing almost always
        # graftlint: ephemeral(dirty counter; the new incarnation restarts at 0)
        self._mutations = 0
        # rank -> departure deadline (unix ts): ranks that announced a
        # preemption drain. Still alive (training until departure), but
        # the post-departure world is already planned — on
        # complete_drain (or a blown deadline) the world re-forms in
        # ONE round instead of waiting out the liveness timeout.
        self._draining: Dict[int, float] = {}
        # peer-to-peer restore (checkpoint/peer_restore.py): rank ->
        # {addr, step, keys, bytes, ts} of the staged state its agent's
        # donor server can serve to a replacement rank
        self._peer_stores: Dict[int, Dict] = {}
        # bumped on EVERY membership loss (death, reap, drain
        # completion): restore plans are stamped with it, and a plan
        # whose epoch no longer matches must not commit — a second
        # failure mid-transfer may have taken the donor (or made the
        # planned world itself stale)
        self._world_epoch = 0
        # -- online parallelism re-planning (parallel/planner.py) ------
        # model profile fields fed from ModelInfo reports + chip-stats
        # HBM totals: what the planner scores candidates against. Empty
        # until the first worker reports — plans computed before that
        # rank on topology alone (still deterministic).
        self._model_profile: Dict[str, float] = {}
        self._chip_hbm_bytes: int = 0
        # the last stamped plan (fleet-wide — in slice mode the plan
        # spans every formed slice with dcn = slice count): its mesh
        # feeds the migration term of the NEXT plan, and a change
        # against it is what counts as a REAL re-plan. The inputs it
        # was computed from memoize the planner: every join and every
        # worker's ShardPlanRequest asks, and re-enumerating the mesh
        # space under the manager lock for identical inputs would
        # serialize liveness-critical RPCs behind pure recomputation.
        self._last_plan: Optional[Dict] = None
        self._last_plan_inputs: Optional[Tuple] = None
        # learned per-axis efficiency discounts from the calibration
        # loop (parallel/calibration.py, pushed by the servicer):
        # part of every plan's deterministic inputs. Deliberately NOT
        # exported — the calibration itself persists and re-pushes
        # after a restore, so the discounts can never outlive their
        # evidence.
        # graftlint: ephemeral(re-pushed via push_axis_discounts)
        self._axis_discounts: Dict[str, float] = {}
        # rank -> chips, remembered across world invalidations: the
        # planner must see the EXPECTED post-re-formation world at the
        # FIRST survivor's join (cut worlds are emptied on a death and
        # the waiting list fills one join at a time — planning only
        # from those would stamp a transient partial-world plan per
        # join and fire N-1 spurious re-plan events)
        self._known_chips: Dict[int, int] = {}
        # -- slice-scoped failure domains ------------------------------
        # rank -> slice id, learned from joins/peer-store reports; any
        # entry (with slice_scoped) switches the manager to per-slice
        # worlds
        self._slices: Dict[int, int] = {}
        self._slice_worlds: Dict[int, Dict[int, int]] = {}
        # per-slice round counters (what join/get_comm_world speak in
        # slice mode) and generation tokens — the PER-SLICE layer over
        # PR 3's global master generation: bumped each time THAT slice's
        # world cuts, provably untouched when a DIFFERENT slice fails
        self._slice_rounds: Dict[int, int] = {}
        self._slice_generation: Dict[int, int] = {}
        self._slice_round_start: Dict[int, float] = {}

    # -- membership (driven by the node manager / event callbacks) --------
    def update_rdzv_params(self, min_nodes: int, max_nodes: int,
                           wait_new_node_s: float = 30.0,
                           node_unit: int = 1) -> None:
        with self._lock:
            self._params = RendezvousParameters(
                min_nodes, max_nodes, wait_new_node_s, node_unit
            )

    @property
    def mutation_count(self) -> int:
        with self._lock:
            return self._mutations

    @property
    def alive_nodes(self) -> set:
        """Ranks currently believed alive (the membership the speed
        monitor / diagnosis engine must not outrank)."""
        with self._lock:
            return set(self._alive_nodes)

    def add_alive_node(self, node_rank: int) -> None:
        with self._lock:
            self._alive_nodes.add(node_rank)
            self._last_seen[node_rank] = time.time()
            self._mutations += 1

    def touch(self, node_rank: int) -> None:
        """Record liveness for a rank (any agent RPC qualifies)."""
        if node_rank < 0:
            return
        with self._lock:
            self._last_seen[node_rank] = time.time()

    # -- slice membership (multi-slice hierarchical DP) --------------------
    def _slice_mode_locked(self) -> bool:
        """(lock held)"""
        return self.slice_scoped and bool(self._slices)

    def _record_slice_locked(self, node_rank: int, slice_id: int) -> None:
        """(lock held)"""
        if slice_id >= 0 and self.slice_scoped:
            if self._slices.get(node_rank) != slice_id:
                self._slices[node_rank] = slice_id
                self._mutations += 1

    def record_slice(self, node_rank: int, slice_id: int) -> None:
        """Teach the registry a rank's slice outside the join path
        (reconnects, peer-store reports that precede the first join)."""
        with self._lock:
            self._record_slice_locked(node_rank, slice_id)

    def slice_of(self, node_rank: int) -> int:
        with self._lock:
            return self._slices.get(node_rank, -1)

    @property
    def slice_map(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._slices)

    def slice_members(self, slice_id: int) -> List[int]:
        with self._lock:
            return sorted(r for r, s in self._slices.items()
                          if s == slice_id)

    def slice_status(self) -> Dict:
        """The registry view the cross-slice gradient sync divides by
        (parallel/dcn_sync.py): which slices are formed right now, with
        their generation tokens. JSON-safe."""
        with self._lock:
            sids = sorted(set(self._slices.values()))
            slices = {}
            for sid in sids:
                members = sorted(r for r, s in self._slices.items()
                                 if s == sid)
                world = self._slice_worlds.get(sid, {})
                slices[str(sid)] = {
                    "formed": bool(world),
                    "ranks": sorted(world) if world else members,
                    "generation": self._slice_generation.get(sid, 0),
                    "draining": any(r in self._draining
                                    for r in members),
                }
            # the world epoch namespaces the hot dcn/ coordination keys
            # (parallel/dcn_sync.py + kv_store episode hygiene): every
            # membership loss moves the fleet to a fresh key namespace
            return {"total": len(sids), "slices": slices,
                    "epoch": self._world_epoch}

    def world_for(self, node_rank: int) -> Dict[int, int]:
        """The world ``node_rank`` belongs to: its slice's world in
        slice mode, the fleet world otherwise (the reconnect handler's
        intact check must compare against the right scope)."""
        with self._lock:
            if self._slice_mode_locked() and node_rank in self._slices:
                return dict(self._slice_worlds.get(
                    self._slices[node_rank], {}))
            return dict(self._latest_world)

    def round_for(self, node_rank: int) -> int:
        """The latest completed round in ``node_rank``'s scope."""
        with self._lock:
            if self._slice_mode_locked() and node_rank in self._slices:
                return self._slice_rounds.get(
                    self._slices[node_rank], 0) - 1
            return self._rdzv_round - 1

    def _slice_ready_locked(self, sid: int) -> bool:
        """A slice's round completes when every alive member joined, or
        the late-node grace expired with at least one waiting (lock
        held). node_unit deliberately does not apply: a slice cuts
        whole — partial slices are what the failure domain forbids."""
        waiting = {r for r in self._waiting
                   if self._slices.get(r) == sid}
        if not waiting:
            return False
        alive = {r for r in self._alive_nodes
                 if self._slices.get(r) == sid}
        if alive and alive.issubset(set(self._waiting)):
            return True
        started = self._slice_round_start.get(sid)
        return (started is not None
                and time.time() - started >= self._params.wait_new_node_s)

    def _cut_slice_locked(self, sid: int):
        """Cut ``sid``'s world from its waiting members (lock held).
        Returns (sid, round, generation, world, duration) for the
        caller's obs emission outside the lock."""
        members = sorted(r for r in self._waiting
                         if self._slices.get(r) == sid)
        world = {r: self._waiting[r].local_world_size for r in members}
        for rank in members:
            del self._waiting[rank]
        self._slice_worlds[sid] = world
        self._slice_rounds[sid] = self._slice_rounds.get(sid, 0) + 1
        self._slice_generation[sid] = (
            self._slice_generation.get(sid, 0) + 1)
        self._mutations += 1
        started = self._slice_round_start.pop(sid, None)
        duration = (max(0.0, time.time() - started)
                    if started is not None else 0.0)
        logger.info(
            "%s rendezvous: slice %d round %d cut (generation %d): "
            "world=%s", self.name, sid, self._slice_rounds[sid] - 1,
            self._slice_generation[sid], sorted(world))
        return (sid, self._slice_rounds[sid] - 1,
                self._slice_generation[sid], dict(world), duration)

    def _emit_slice_cut_obs(self, cut) -> None:
        """Flight + metrics for a just-cut slice world (called OUTSIDE
        the manager lock)."""
        sid, round_idx, generation, world, duration = cut
        obs.get_flight_recorder().record_event(
            "slice_world_cut", rdzv=self.name, slice=sid,
            round=round_idx, generation=generation,
            world=sorted(world))
        obs.record_span(
            "rendezvous_round", duration,
            attrs={"rdzv": self.name, "round": round_idx, "slice": sid,
                   "world_size": len(world)})
        registry = obs.get_registry()
        registry.counter(
            "dlrover_tpu_rendezvous_rounds_total",
            "Completed rendezvous rounds", labelnames=("rdzv",),
        ).labels(rdzv=self.name).inc()
        registry.gauge(
            "dlrover_tpu_slice_generation",
            "Per-slice generation token: bumped each time THAT slice's "
            "world re-forms (a peer slice's failure must not move it)",
            labelnames=("slice",)).labels(slice=str(sid)).set(generation)
        registry.gauge(
            "dlrover_tpu_slice_world_size",
            "Node count of the slice's latest cut world",
            labelnames=("slice",)).labels(slice=str(sid)).set(len(world))

    # -- preemption drain --------------------------------------------------
    def _publish_draining_gauge(self) -> None:
        """Republished by EVERY path that mutates the draining set
        (notice, completion, blown-deadline reap, re-join cancel,
        death, state restore) — updating it only on the drain RPC
        would leave phantom perpetually-draining ranks on the others.
        Called OUTSIDE the manager lock (obs takes its own)."""
        if self.name != RendezvousName.TRAINING:
            return
        with self._lock:
            count = len(self._draining)
        obs.get_registry().gauge(
            "dlrover_tpu_draining_nodes",
            "Ranks currently draining (announced, not yet departed)",
        ).set(count)

    def mark_draining(self, node_rank: int, deadline: float
                      ) -> Dict[int, int]:
        """A preemption notice for ``node_rank``: it keeps training
        until departure, but the post-departure world is planned NOW.
        Returns that planned world (latest world minus every draining
        rank) so the caller can log/verify the one-round target."""
        with self._lock:
            if node_rank in self._alive_nodes:
                self._draining[node_rank] = deadline
                self._mutations += 1
            if (self._slice_mode_locked()
                    and node_rank in self._slices):
                base_world = self._slice_worlds.get(
                    self._slices[node_rank], {})
            else:
                base_world = self._latest_world
            planned = {rank: n for rank, n in base_world.items()
                       if rank not in self._draining}
        logger.info(
            "%s rendezvous: node %d DRAINING (deadline %.0fs away); "
            "planned post-departure world %s", self.name, node_rank,
            max(0.0, deadline - time.time()), sorted(planned))
        self._publish_draining_gauge()
        return planned

    def complete_drain(self, node_rank: int) -> bool:
        """The drained worker exited clean: remove the rank immediately
        (planned departure — no liveness timeout) so survivors re-form
        in one round. Returns whether the rank was known draining."""
        with self._lock:
            was_draining = self._draining.pop(node_rank, None) is not None
        # NOT graceful: the cut world contained the drained rank, so
        # survivors must re-join for the planned smaller world — and
        # with the rank out of the alive set the new round cuts as soon
        # as the last survivor joins (no wait_new_node_s stall)
        self.remove_alive_node(node_rank, graceful=False)
        obs.get_flight_recorder().record_event(
            "node_drained", rdzv=self.name, rank=node_rank,
            announced=was_draining)
        return was_draining

    @property
    def draining(self) -> Dict[int, float]:
        with self._lock:
            return dict(self._draining)

    # -- peer-to-peer restore (checkpoint/peer_restore.py) -----------------
    @property
    def world_epoch(self) -> int:
        with self._lock:
            return self._world_epoch

    def register_peer_store(self, node_rank: int, addr: str, step: int,
                            keys, total_bytes: int = 0,
                            slice_id: int = -1) -> None:
        """An agent advertising (or withdrawing: step < 0 / no keys) the
        staged state its donor server can serve. ``slice_id`` also
        teaches the slice registry — store reports land BEFORE the
        join, and a restarted master must know the donor's slice to
        tier the plan."""
        with self._lock:
            self._record_slice_locked(node_rank, slice_id)
            if step < 0 or not keys:
                if self._peer_stores.pop(node_rank, None) is not None:
                    self._mutations += 1
                return
            self._peer_stores[node_rank] = {
                "addr": addr, "step": int(step), "keys": list(keys),
                "bytes": int(total_bytes), "ts": time.time(),
            }
            self._mutations += 1

    @property
    def peer_stores(self) -> Dict[int, Dict]:
        with self._lock:
            return {rank: dict(s) for rank, s in self._peer_stores.items()}

    # -- online parallelism re-planning (parallel/planner.py) --------------
    def set_model_profile(self, param_count: int = 0,
                          param_bytes: int = 0,
                          flops_per_token: float = 0.0,
                          peak_flops_per_chip: float = 0.0,
                          seq_len: int = 0,
                          global_batch: int = 0,
                          tensor_divisor: int = 0,
                          fsdp_divisor: int = 0) -> None:
        """Teach the planner the model's shape (fed from ModelInfo
        reports by the servicer). Zero fields leave the previous value
        standing — a cross-check re-report that only updates the FLOPs
        model must not erase the batch."""
        updates = {"param_count": param_count, "param_bytes": param_bytes,
                   "flops_per_token": flops_per_token,
                   "peak_flops_per_chip": peak_flops_per_chip,
                   "seq_len": seq_len, "global_batch": global_batch,
                   "tensor_divisor": tensor_divisor,
                   "fsdp_divisor": fsdp_divisor}
        with self._lock:
            for key, value in updates.items():
                if value and value > 0:
                    if self._model_profile.get(key) != value:
                        self._model_profile[key] = value
                        self._mutations += 1

    def set_chip_hbm(self, hbm_bytes: int) -> None:
        """Observed per-chip HBM total (from NodeResourceStats chip
        stats): the planner's memory-fit budget. 0 stays 0 —
        unconstrained (CPU harnesses)."""
        with self._lock:
            if hbm_bytes > 0 and self._chip_hbm_bytes != int(hbm_bytes):
                self._chip_hbm_bytes = int(hbm_bytes)
                self._mutations += 1

    def set_axis_discounts(self, discounts: Dict[str, float]) -> None:
        """Learned per-axis efficiency corrections from the calibration
        loop (parallel/calibration.py, pushed by the servicer when the
        learned table changes): scoring input for every later plan.
        Changing them invalidates the plan memo (they are part of its
        inputs) but deliberately does not bump the mutation counter —
        derived state, re-pushed from the persisted calibration."""
        with self._lock:
            self._axis_discounts = {str(k): float(v)
                                    for k, v in (discounts or {}).items()
                                    if v and v > 0}

    def _plan_world_locked(self) -> Dict[int, int]:
        """(lock held) The world the next plan must cover: every alive,
        non-draining rank — cut worlds and the waiting list give the
        freshest chip counts, the remembered ``_known_chips`` covers
        survivors that have not re-joined yet (their world was
        invalidated an instant ago, but they ARE part of the world
        that is about to form). Planning from the full expected set
        means the FIRST join after a membership change already sees
        the final plan — one re-plan per resize, not one per joiner."""
        chips: Dict[int, int] = dict(self._known_chips)
        if self._slice_mode_locked():
            for world in self._slice_worlds.values():
                chips.update(world)
        else:
            chips.update(self._latest_world)
        for rank, waiting in self._waiting.items():
            chips[rank] = waiting.local_world_size
        return {rank: int(n) for rank, n in chips.items()
                if rank in self._alive_nodes
                and rank not in self._draining}

    def compute_shard_plan(self, node_rank: int) -> Tuple[Dict, bool]:
        """The deterministic parallelism plan for the (forming) world
        ``node_rank`` belongs to (parallel/planner.py): DP×TP×PP(×DCN)
        mesh + batch/accumulation shape, stamped with the rendezvous
        generation token and the world epoch (same staleness
        discipline as restore plans). Returns (plan, changed) —
        ``changed`` is True when the plan's execution shape differs
        from the last stamped one (a REAL re-plan, not a re-stamp for
        a late joiner)."""
        from dlrover_tpu.parallel import planner

        with self._lock:
            world = self._plan_world_locked()
            slices = (len({self._slices.get(r, -1) for r in world})
                      if self._slice_mode_locked() and world else 1)
            profile = planner.ModelProfile(
                param_count=int(self._model_profile.get(
                    "param_count", 0)),
                param_bytes=int(self._model_profile.get(
                    "param_bytes", 0)),
                flops_per_token=float(self._model_profile.get(
                    "flops_per_token", 0.0)),
                peak_flops_per_chip=float(self._model_profile.get(
                    "peak_flops_per_chip", 0.0)),
                seq_len=int(self._model_profile.get("seq_len", 0)),
                global_batch=int(self._model_profile.get(
                    "global_batch", 0)),
                hbm_bytes_per_chip=self._chip_hbm_bytes,
                tensor_divisor=int(self._model_profile.get(
                    "tensor_divisor", 0)),
                fsdp_divisor=int(self._model_profile.get(
                    "fsdp_divisor", 0)),
            )
            if self._slice_mode_locked() and node_rank in self._slices:
                sid = self._slices[node_rank]
                generation = self._slice_generation.get(sid, 0)
                round_ = self._slice_rounds.get(sid, 0)
            else:
                generation = self._rdzv_round
                round_ = self._rdzv_round
            discounts = dict(self._axis_discounts)
            inputs = (tuple(sorted(world.items())), profile,
                      max(1, slices), generation, self._world_epoch,
                      round_, tuple(sorted(discounts.items())))
            if (self._last_plan is not None
                    and inputs == self._last_plan_inputs):
                # identical inputs → identical (deterministic) plan:
                # answer the memo instead of re-enumerating the mesh
                # space under the lock for every join/plan poll
                return dict(self._last_plan), False
            plan = planner.plan_parallelism(
                world, profile, slices=max(1, slices),
                prev_plan=self._last_plan, generation=generation,
                epoch=self._world_epoch, round_=round_,
                axis_discounts=discounts or None)
            self._last_plan_inputs = inputs
            equivalent = planner.plans_equivalent(self._last_plan, plan)
            # a REAL re-plan needs a previous plan to differ from AND a
            # world that has ever formed — bootstrap joins refine the
            # first plan as members arrive, which is formation, not a
            # resize (no replan events, no MFU re-anchor churn)
            has_cut = (any(self._slice_rounds.values())
                       if self._slice_mode_locked()
                       else self._rdzv_round > 0)
            changed = (self._last_plan is not None and has_cut
                       and not equivalent)
            prev = None
            if not equivalent:
                prev = self._last_plan
                self._last_plan = plan
                self._mutations += 1
        if changed and prev is not None:
            obs.get_flight_recorder().record_event(
                "replan_stamped", rdzv=self.name,
                world_size=plan.get("world_size"),
                devices=plan.get("total_devices"),
                mesh=plan.get("mesh"), prev_mesh=prev.get("mesh"),
                global_batch=plan.get("global_batch"),
                batch_adjusted=plan.get("batch_adjusted"),
                resharded=plan.get("resharded"),
                generation=plan.get("generation"),
                epoch=plan.get("epoch"))
        return plan, changed

    @property
    def last_shard_plan(self) -> Optional[Dict]:
        with self._lock:
            return dict(self._last_plan) if self._last_plan else None

    def compute_restore_plan(self, node_rank: int,
                             stripe: bool = False) -> Dict:
        """For each staged shard a restoring rank may need, which
        surviving donor serves it. Donors: alive, not draining, staged
        at the newest common step (mixing steps would assemble a state
        that never existed). The requester's own store wins for shards
        it holds (a local read beats the network); the rest prefer
        SAME-SLICE donors (ICI bandwidth) before cross-slice (DCN)
        ones, round-robin within each tier. Stamped with the world
        epoch — the staleness guard. Pure dict work under the lock;
        JSON encoding is the caller's business.

        ``stripe`` (the re-plan migration mode): each entry lists EVERY
        same-step holder (same-slice donors first) so the receiver can
        fetch contiguous byte RANGES of one shard from several donors
        in parallel — who sends which shard slice to whom when the
        target sharding differs from the source
        (checkpoint/peer_restore.py ``fetch_shards``)."""
        with self._lock:
            stores = {
                rank: store
                for rank, store in self._peer_stores.items()
                if rank in self._alive_nodes
                and rank not in self._draining
            }
            plan = plan_restore_entries(stores, node_rank, self._slices,
                                        stripe=stripe)
            plan["epoch"] = self._world_epoch
            if stripe:
                plan["mode"] = "stripe"
            return plan

    def export_protocol_view(self) -> Dict:
        """One-lock-cut view of the protocol membership (the sharded
        router aggregates these per shard for fleet-wide planning —
        master/rendezvous_shards.py)."""
        with self._lock:
            return {
                "world": dict(self._latest_world),
                "waiting": {r: w.local_world_size
                            for r, w in self._waiting.items()},
                "alive": set(self._alive_nodes),
                "draining": dict(self._draining),
            }

    def reap_dead_nodes(self, timeout_s: float) -> None:
        """Declare ranks silent for > timeout_s dead (world invalidation
        via remove_alive_node). 0/negative disables. Runs on live agents'
        polls — no master thread needed, and with no live agents there is
        nobody left to tell anyway.

        Draining ranks whose departure deadline passed are reaped
        regardless of the liveness timeout: the platform took the VM at
        the deadline even if the drain-complete RPC was lost."""
        now = time.time()
        with self._lock:
            overdue = [rank for rank, deadline in self._draining.items()
                       if now > deadline + 5.0]
            for rank in overdue:
                del self._draining[rank]
        for rank in overdue:
            logger.warning(
                "%s rendezvous: draining node %d blew its departure "
                "deadline without reporting completion; removing it",
                self.name, rank)
            self.remove_alive_node(rank, graceful=False)
        if timeout_s <= 0:
            return
        with self._lock:
            dead = [rank for rank in self._alive_nodes
                    if now - self._last_seen.get(rank, now) > timeout_s]
        for rank in dead:
            logger.warning(
                "%s rendezvous: node %d silent for > %.0fs; declaring it "
                "dead", self.name, rank, timeout_s)
            self.remove_alive_node(rank, graceful=False)

    def remove_alive_node(self, node_rank: int,
                          graceful: bool = False) -> None:
        """Drop a node from membership. ``graceful`` marks a clean exit
        (worker finished): survivors keep running, so the cut world stays
        valid for them and must NOT be invalidated — only a death does."""
        invalidated_round = None
        slice_invalidated = None
        with self._lock:
            in_slice_world = any(
                node_rank in world
                for world in self._slice_worlds.values())
            if (node_rank in self._alive_nodes
                    or node_rank in self._latest_world
                    or in_slice_world):
                # a real membership loss: any restore plan computed
                # before this instant may name the departed rank as a
                # donor — the epoch bump invalidates it at commit time
                self._world_epoch += 1
            self._alive_nodes.discard(node_rank)
            self._waiting.pop(node_rank, None)
            self._pending_rejoin.discard(node_rank)
            self._draining.pop(node_rank, None)
            # the host's staged state goes with the host
            self._peer_stores.pop(node_rank, None)
            self._mutations += 1
            if self._slice_mode_locked():
                # SLICE-SCOPED cut: only the dead rank's slice loses
                # its world. Every other slice's world, round counter
                # and generation token are deliberately untouched —
                # that is the failure-domain contract. (The rank keeps
                # its slice-map entry: it re-joins as the same slice.)
                sid = self._slices.get(node_rank, -1)
                world = self._slice_worlds.get(sid, {})
                if not graceful and node_rank in world:
                    logger.info(
                        "%s rendezvous: node %d died after slice %d "
                        "round %d was cut; invalidating ONLY that "
                        "slice's world (fleet unaffected)", self.name,
                        node_rank, sid,
                        self._slice_rounds.get(sid, 1) - 1)
                    self._pending_rejoin |= set(world) - {node_rank}
                    self._slice_worlds[sid] = {}
                    slice_invalidated = (
                        sid, self._slice_rounds.get(sid, 1) - 1)
            elif not graceful and node_rank in self._latest_world:
                # A member of the cut round died: any survivor handed this
                # world would only find out at jax.distributed.initialize
                # timeout. Empty it so polls report "still forming" and
                # survivors re-join for a fresh round.
                logger.info(
                    "%s rendezvous: node %d died after round %d was cut; "
                    "invalidating the world", self.name, node_rank,
                    self._rdzv_round - 1,
                )
                self._pending_rejoin |= (
                    set(self._latest_world) - {node_rank}
                )
                self._latest_world = {}
                self._on_world_invalidated()
                invalidated_round = self._rdzv_round - 1
        # obs sinks run OUTSIDE the manager lock (they take their own)
        self._publish_draining_gauge()
        if slice_invalidated is not None:
            sid, round_idx = slice_invalidated
            obs.get_flight_recorder().record_event(
                "slice_world_invalidated", rdzv=self.name, slice=sid,
                dead_rank=node_rank, round=round_idx)
            obs.get_registry().counter(
                "dlrover_tpu_rendezvous_world_invalidations_total",
                "Cut worlds invalidated by a member death",
                labelnames=("rdzv",),
            ).labels(rdzv=self.name).inc()
        if invalidated_round is not None:
            self._emit_invalidation_obs(node_rank, invalidated_round)

    def _emit_invalidation_obs(self, node_rank: int,
                               invalidated_round: int) -> None:
        """Flight + metrics for an invalidated cut world (called OUTSIDE
        the manager lock; shard inners override it to emit the
        slice-labeled variant — master/rendezvous_shards.py)."""
        obs.get_flight_recorder().record_event(
            "world_invalidated", rdzv=self.name,
            dead_rank=node_rank, round=invalidated_round)
        obs.get_registry().counter(
            "dlrover_tpu_rendezvous_world_invalidations_total",
            "Cut worlds invalidated by a member death",
            labelnames=("rdzv",),
        ).labels(rdzv=self.name).inc()

    def _on_world_invalidated(self) -> None:
        """Hook for subclasses holding state keyed on the cut world
        (lock held)."""

    # -- agent-facing protocol --------------------------------------------
    def join_rendezvous(self, node_rank: int, local_world_size: int,
                        node_ip: str = "", slice_id: int = -1) -> int:
        """Register a joiner; returns the round it will be placed in
        (its SLICE's round in slice mode)."""
        with self._lock:
            self._record_slice_locked(node_rank, slice_id)
            self._waiting[node_rank] = _WaitingNode(node_rank,
                                                    local_world_size)
            # the planner's expected-world chip map (kept across world
            # invalidations; see _plan_world_locked)
            self._known_chips[node_rank] = local_world_size
            self._alive_nodes.add(node_rank)
            self._last_seen[node_rank] = time.time()
            self._pending_rejoin.discard(node_rank)
            # a re-joining rank is no longer departing (drain cancelled
            # operator-side, or the platform gave the VM back)
            self._draining.pop(node_rank, None)
            if node_ip:
                self._node_ips[node_rank] = node_ip
            if len(self._waiting) == 1:
                self._latest_round_start = time.time()
            self._mutations += 1
            if (self._slice_mode_locked()
                    and node_rank in self._slices):
                sid = self._slices[node_rank]
                # the slice's grace window is timed from ITS first
                # waiting member, not the fleet's (test membership,
                # not rank truthiness — rank 0 is falsy)
                others_waiting = any(
                    r != node_rank and self._slices.get(r) == sid
                    for r in self._waiting)
                if not others_waiting:
                    self._slice_round_start[sid] = time.time()
                joined_round = self._slice_rounds.get(sid, 0)
            else:
                joined_round = self._rdzv_round
        obs.get_registry().counter(
            "dlrover_tpu_rendezvous_joins_total",
            "join_rendezvous RPCs accepted", labelnames=("rdzv",),
        ).labels(rdzv=self.name).inc()
        self._publish_draining_gauge()
        return joined_round

    def leave_waiting(self, node_rank: int) -> None:
        """A joiner abandoning an UNCOMPLETED round (its poll deadline
        expired). Its entry must not linger: a late partner would
        otherwise complete the round against a peer that already left
        and hang waiting for that peer's coordinator. The node stays
        alive (it may re-join); a no-op after the round cut."""
        with self._lock:
            if self._waiting.pop(node_rank, None) is not None:
                self._mutations += 1
                logger.info(
                    "%s rendezvous: node %d left the waiting list "
                    "(gave up on the forming round)", self.name,
                    node_rank)

    def get_comm_world(self, node_rank: int
                       ) -> Tuple[int, int, Dict[int, int]]:
        """Poll for the completed world. Returns (round, group, world) —
        empty world while the round is still forming. In slice mode the
        world is the polling rank's SLICE world and ``group`` carries
        the slice id."""
        cut_info = None
        slice_cut = None
        with self._lock:
            self._last_seen[node_rank] = time.time()
            if (self._slice_mode_locked()
                    and node_rank in self._slices):
                sid = self._slices[node_rank]
                if self._slice_ready_locked(sid):
                    slice_cut = self._cut_slice_locked(sid)
                world = self._slice_worlds.get(sid, {})
                if (node_rank in world
                        and node_rank not in self._waiting):
                    result = (self._slice_rounds.get(sid, 1) - 1, sid,
                              dict(world))
                else:
                    result = self._slice_rounds.get(sid, 0), sid, {}
            else:
                if self._check_rdzv_completed():
                    cut_info = self._cut_round()
                # A node still in the waiting list has re-joined for the
                # NEXT round — the latest world is stale for it (it may
                # contain dead peers), so report "still forming".
                if (node_rank in self._latest_world
                        and node_rank not in self._waiting):
                    result = (self._rdzv_round - 1, 0,
                              dict(self._latest_world))
                else:
                    result = self._rdzv_round, 0, {}
        if slice_cut is not None:
            self._emit_slice_cut_obs(slice_cut)
        if cut_info is not None:
            self._emit_round_obs(cut_info)
        return result

    def num_nodes_waiting(self, node_rank: int = -1) -> int:
        """Agents restart workers when >0 while healthy (membership change;
        reference: training.py:483-486). In slice mode the signal is
        scoped to the POLLING rank's slice: a peer slice re-forming must
        not restart this slice's worker — that is the failure domain."""
        with self._lock:
            if (self._slice_mode_locked() and node_rank >= 0
                    and node_rank in self._slices):
                sid = self._slices[node_rank]
                members = {r for r, s in self._slices.items()
                           if s == sid}
                waiting = set(self._waiting) & members
                if self._pending_rejoin & members:
                    return max(1, len(waiting))
                if not self._slice_worlds.get(sid):
                    return 0
                return len(waiting)
            if self._pending_rejoin:
                # A world member died: every survivor must restart and
                # re-join; keep the signal raised until each has done so
                # (or died), however late its poll arrives.
                return max(1, len(self._waiting))
            # Before the first round there is no world to change.
            if not self._latest_world:
                return 0
            return len(self._waiting)

    # -- internals ---------------------------------------------------------
    def _check_rdzv_completed(self) -> bool:
        """Round completes when every alive node joined, or min_nodes joined
        and the late-node grace window expired (lock held)."""
        if not self._waiting:
            return False
        if self._slice_mode_locked() and all(
                rank in self._slices for rank in self._waiting):
            # slice mode: every waiting rank belongs to a slice — the
            # per-slice cut path owns them; a sliceless poller must not
            # sweep them into a fleet round
            return False
        num = min(len(self._waiting), self._params.max_nodes)
        if num < self._params.min_nodes:
            return False
        alive_all_joined = (
            self._alive_nodes
            and self._alive_nodes.issubset(set(self._waiting))
        )
        if num == self._params.max_nodes or alive_all_joined:
            return self._rounded_size(num) >= self._params.min_nodes
        waited = time.time() - self._latest_round_start
        if waited >= self._params.wait_new_node_s:
            return self._rounded_size(num) >= self._params.min_nodes
        return False

    def _rounded_size(self, num: int) -> int:
        unit = max(1, self._params.node_unit)
        return (num // unit) * unit

    def _cut_round(self):
        """Select the world for this round (lock held). Returns
        (duration_s, round_idx, world_size, world_ranks) for the caller
        to pass to `_emit_round_obs` once the lock is released."""
        size = self._rounded_size(
            min(len(self._waiting), self._params.max_nodes)
        )
        # Keep the lowest-ranked `size` nodes; the rest stay waiting for the
        # next round (node_unit remainder).
        chosen = sorted(self._waiting)[:size]
        self._latest_world = {
            rank: self._waiting[rank].local_world_size for rank in chosen
        }
        for rank in chosen:
            del self._waiting[rank]
        self._rdzv_round += 1
        self._mutations += 1
        logger.info(
            "%s rendezvous round %d completed: world=%s",
            self.name, self._rdzv_round - 1, sorted(self._latest_world),
        )
        duration = max(0.0, time.time() - self._latest_round_start)
        if self._waiting:
            # a node_unit remainder stays waiting: it opens the NEXT
            # forming round now (the len==1 transition in join_rendezvous
            # will never fire for it, so the next round's span/grace
            # window must not be timed from the OLD round's first join)
            self._latest_round_start = time.time()
        return (duration, self._rdzv_round - 1, len(self._latest_world),
                sorted(self._latest_world))

    def _emit_round_obs(self, cut_info) -> None:
        """Round span + counters for a just-cut round. Called AFTER the
        manager lock is released — span sinks and registry children take
        their own locks and must never nest under it."""
        duration_s, round_idx, world_size, _ = cut_info
        obs.record_span(
            "rendezvous_round", duration_s,
            attrs={"rdzv": self.name, "round": round_idx,
                   "world_size": world_size},
        )
        registry = obs.get_registry()
        registry.counter(
            "dlrover_tpu_rendezvous_rounds_total",
            "Completed rendezvous rounds", labelnames=("rdzv",),
        ).labels(rdzv=self.name).inc()
        registry.gauge(
            "dlrover_tpu_rendezvous_world_size",
            "Node count of the latest cut world", labelnames=("rdzv",),
        ).labels(rdzv=self.name).set(world_size)

    @property
    def latest_world(self) -> Dict[int, int]:
        """The fleet view: the union of slice worlds in slice mode."""
        with self._lock:
            if self._slice_mode_locked():
                merged: Dict[int, int] = {}
                for world in self._slice_worlds.values():
                    merged.update(world)
                return merged
            return dict(self._latest_world)

    @property
    def rdzv_round(self) -> int:
        with self._lock:
            return self._rdzv_round

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        """JSON-safe snapshot of the rendezvous protocol state. Liveness
        clocks (_last_seen) are NOT exported: wall time on the restarted
        master restarts them, and exporting stale clocks would reap every
        member the instant the new master serves its first poll."""
        with self._lock:
            state = {
                "round": self._rdzv_round,
                "latest_world": {str(r): n
                                 for r, n in self._latest_world.items()},
                "waiting": {str(r): w.local_world_size
                            for r, w in self._waiting.items()},
                "alive": sorted(self._alive_nodes),
                "pending_rejoin": sorted(self._pending_rejoin),
                "node_ips": {str(r): ip
                             for r, ip in self._node_ips.items()},
                "draining": {str(r): deadline
                             for r, deadline in self._draining.items()},
                "world_epoch": self._world_epoch,
                "peer_stores": {
                    str(r): {"addr": s["addr"], "step": s["step"],
                             "keys": list(s["keys"]),
                             "bytes": s.get("bytes", 0)}
                    for r, s in self._peer_stores.items()
                },
                # slice-scoped failure domains: membership, per-slice
                # worlds and the generation tokens must survive a
                # master failover — a restarted master that forgot the
                # tokens would hand every slice a fresh generation and
                # erase the "untouched survivor" evidence
                "slices": {str(r): s for r, s in self._slices.items()},
                "slice_worlds": {
                    str(sid): {str(r): n for r, n in world.items()}
                    for sid, world in self._slice_worlds.items()
                },
                "slice_rounds": {str(sid): r for sid, r
                                 in self._slice_rounds.items()},
                "slice_generation": {
                    str(sid): g for sid, g
                    in self._slice_generation.items()},
                # online re-planning: the model profile and the last
                # stamped plan must survive a master failover — a
                # restarted master that forgot them would stamp a
                # migration-blind plan (and mis-detect a "re-plan")
                # on the first join it serves
                "model_profile": dict(self._model_profile),
                "chip_hbm_bytes": self._chip_hbm_bytes,
                "last_plan": (dict(self._last_plan)
                              if self._last_plan else None),
                "known_chips": {str(r): n for r, n
                                in self._known_chips.items()},
            }
            # subclass fields join the SAME cut: one lock acquisition,
            # never two cuts with a mutation in between
            self._export_extra(state)
            return state

    def _export_extra(self, state: dict) -> None:
        """Subclass hook appending extra exported fields (lock held)."""

    def restore_state(self, state: dict) -> None:
        if "shards" in state:
            # a SHARDED master wrote this lineage; flatten its per-shard
            # partitions instead of silently restoring an empty protocol
            # state (the rdzv_sharded=0 escape hatch must keep working
            # over an existing sharded state-dir)
            from dlrover_tpu.master.rendezvous_shards import (
                flatten_sharded_state,
            )

            state = flatten_sharded_state(state)
        now = time.time()
        with self._lock:
            self._rdzv_round = int(state.get("round", 0))
            self._latest_world = {
                int(r): int(n)
                for r, n in state.get("latest_world", {}).items()
            }
            self._waiting = {
                int(r): _WaitingNode(int(r), int(n), join_time=now)
                for r, n in state.get("waiting", {}).items()
            }
            self._alive_nodes = {int(r) for r in state.get("alive", ())}
            self._pending_rejoin = {
                int(r) for r in state.get("pending_rejoin", ())
            }
            self._node_ips = {int(r): ip
                              for r, ip in state.get("node_ips",
                                                     {}).items()}
            # absolute deadlines survive the restart as-is: a drain
            # announced before the master died is still a drain, and a
            # blown deadline is reaped on the first poll
            self._draining = {int(r): float(d)
                              for r, d in state.get("draining",
                                                    {}).items()}
            # a restored plan epoch keeps in-flight plans valid across a
            # master failover — the membership they were computed from
            # was restored with them; peer stores re-register within a
            # monitor tick anyway, but restoring them means a restore
            # landing mid-failover still gets a plan
            self._world_epoch = int(state.get("world_epoch", 0))
            self._peer_stores = {
                int(r): {"addr": s.get("addr", ""),
                         "step": int(s.get("step", -1)),
                         "keys": list(s.get("keys", ())),
                         "bytes": int(s.get("bytes", 0)),
                         "ts": now}
                for r, s in state.get("peer_stores", {}).items()
            }
            self._slices = {int(r): int(s) for r, s in
                            (state.get("slices") or {}).items()}
            self._slice_worlds = {
                int(sid): {int(r): int(n) for r, n in world.items()}
                for sid, world in
                (state.get("slice_worlds") or {}).items()
            }
            self._slice_rounds = {
                int(sid): int(r) for sid, r in
                (state.get("slice_rounds") or {}).items()}
            self._slice_generation = {
                int(sid): int(g) for sid, g in
                (state.get("slice_generation") or {}).items()}
            self._model_profile = {
                str(k): float(v) for k, v in
                (state.get("model_profile") or {}).items()}
            self._chip_hbm_bytes = int(state.get("chip_hbm_bytes", 0))
            last_plan = state.get("last_plan")
            self._last_plan = (dict(last_plan)
                               if isinstance(last_plan, dict) else None)
            self._known_chips = {
                int(r): int(n) for r, n in
                (state.get("known_chips") or {}).items()}
            # the memo key is not exported: the first post-restore ask
            # recomputes (and, being deterministic, re-stamps the same
            # plan without a spurious changed flag)
            self._last_plan_inputs = None
            self._slice_round_start = {}
            # every restored member gets a fresh liveness clock: agents
            # re-register within their poll interval, the genuinely dead
            # age out through the normal reap path
            self._last_seen = {rank: now for rank in self._alive_nodes}
            self._latest_round_start = now
            self._restore_extra(state)
        self._publish_draining_gauge()

    def _restore_extra(self, state: dict) -> None:
        """Subclass hook restoring extra exported fields (lock held)."""


class ElasticTrainingRendezvousManager(RendezvousManager):
    name = "elastic-training"


class NetworkCheckRendezvousManager(RendezvousManager):
    """2-round diagnostic rendezvous (reference: rdzv_manager.py:248-461).

    Deliberately NOT slice-scoped (``slice_scoped = False``): the probe
    pairs across the whole fleet — cross-slice DCN links are part of
    what it checks.

    Round 0 groups adjacent pairs; round 1 re-pairs fastest-with-slowest so a
    node that failed round 0 is re-tested against a known-good partner. On a
    TPU slice the pair maps to a 2-host sub-mesh probe program (allgather over
    ICI/DCN); see dlrover_tpu/diagnostics/network_check.py.
    """

    name = "network-check"
    slice_scoped = False

    def __init__(self, params: Optional[RendezvousParameters] = None):
        super().__init__(params)
        # round -> {node_rank: (normal, elapsed_time)}
        self._reports: Dict[int, Dict[int, Tuple[bool, float]]] = {}
        self._check_round = 0
        self._groups: Dict[int, List[List[int]]] = {}

    def get_comm_world(self, node_rank: int
                       ) -> Tuple[int, int, Dict[int, int]]:
        cut_info = None
        result = None
        with self._lock:
            self._last_seen[node_rank] = time.time()
            if self._check_rdzv_completed():
                cut_info = self._cut_round()
                self._groups[self._rdzv_round - 1] = self._group_nodes(
                    self._check_round
                )
                self._check_round += 1
            round_idx = self._rdzv_round - 1
            groups = self._groups.get(round_idx, [])
            for gi, group in enumerate(groups):
                if (node_rank in group
                        and all(r in self._latest_world for r in group)):
                    world = {r: self._latest_world[r] for r in group}
                    result = round_idx, gi, world
                    break
            if result is None:
                result = self._rdzv_round, 0, {}
        if cut_info is not None:
            self._emit_round_obs(cut_info)
        return result

    def _on_world_invalidated(self) -> None:
        # Groups are keyed on the cut world; a member death makes the
        # latest round's grouping stale (lock held).
        self._groups.pop(self._rdzv_round - 1, None)

    def _group_nodes(self, check_round: int) -> List[List[int]]:
        """Pair nodes for the probe (lock held). Round 0: adjacent pairs.
        Round ≥1: sort by last round's elapsed time, pair fastest with
        slowest (reference: rdzv_manager.py:299-346)."""
        ranks = sorted(self._latest_world)
        if check_round == 0 or not self._reports.get(check_round - 1):
            pairs = [ranks[i:i + 2] for i in range(0, len(ranks), 2)]
        else:
            prev = self._reports[check_round - 1]
            by_time = sorted(
                ranks, key=lambda r: prev.get(r, (False, float("inf")))[1]
            )
            pairs = []
            lo, hi = 0, len(by_time) - 1
            while lo < hi:
                pairs.append([by_time[lo], by_time[hi]])
                lo += 1
                hi -= 1
            if lo == hi:
                pairs.append([by_time[lo]])
        # Merge a trailing singleton into the previous pair so it has a peer.
        if pairs and len(pairs[-1]) == 1 and len(pairs) > 1:
            pairs[-2].extend(pairs.pop())
        return pairs

    def report_network_status(self, node_rank: int, normal: bool,
                              elapsed_time: float) -> None:
        with self._lock:
            round_reports = self._reports.setdefault(
                self._check_round - 1 if self._check_round else 0, {}
            )
            round_reports[node_rank] = (normal, elapsed_time)

    def join_rendezvous(self, node_rank: int, local_world_size: int,
                        node_ip: str = "", slice_id: int = -1) -> int:
        with self._lock:
            if not self._waiting and self._check_round >= 2:
                # A full 2-round check cycle was consumed; a new joiner starts
                # a fresh cycle with a clean slate of verdicts.
                self._reports.clear()
                self._groups.clear()
                self._check_round = 0
        return super().join_rendezvous(node_rank, local_world_size, node_ip,
                                       slice_id)

    def check_fault_node(self) -> Tuple[List[int], int]:
        """Nodes abnormal in ALL reported rounds are faulty (reference:
        check_fault_node rdzv_manager.py:399). Returns (fault_nodes,
        rounds_reported)."""
        with self._lock:
            if not self._reports:
                return [], 0
            fault: Optional[set] = None
            for round_reports in self._reports.values():
                bad = {r for r, (ok, _) in round_reports.items() if not ok}
                fault = bad if fault is None else (fault & bad)
            return sorted(fault or ()), len(self._reports)

    def detect_stragglers(self) -> List[int]:
        """elapsed > ratio × median in the latest round (reference:
        _detect_stragglers rdzv_manager.py:446)."""
        ratio = Context.singleton().straggler_median_ratio
        with self._lock:
            if not self._reports:
                return []
            latest = self._reports[max(self._reports)]
            times = [t for ok, t in latest.values() if t > 0]
            if len(times) < 2:
                return []
            median = statistics.median(times)
            return sorted(
                r for r, (ok, t) in latest.items() if t > ratio * median
            )

    def network_check_success(self) -> bool:
        fault, rounds = self.check_fault_node()
        return rounds > 0 and not fault

    def _export_extra(self, state: dict) -> None:
        """Check-cycle fields join the base export's cut (lock held)."""
        state["check_round"] = self._check_round
        state["reports"] = {
            str(rnd): {str(r): [ok, t]
                       for r, (ok, t) in reports.items()}
            for rnd, reports in self._reports.items()
        }
        state["groups"] = {
            str(rnd): groups
            for rnd, groups in self._groups.items()
        }

    def _restore_extra(self, state: dict) -> None:
        """(lock held)"""
        self._check_round = int(state.get("check_round", 0))
        self._reports = {
            int(rnd): {int(r): (bool(v[0]), float(v[1]))
                       for r, v in reports.items()}
            for rnd, reports in state.get("reports", {}).items()
        }
        self._groups = {
            int(rnd): [[int(r) for r in group] for group in groups]
            for rnd, groups in state.get("groups", {}).items()
        }
