"""Sharded rendezvous control plane: one shard per ICI slice.

PR 8 made the slice the FAILURE domain — per-slice worlds, rounds and
generation tokens — but every slice still serialized on one manager lock:
a wedged or slow slice's joins delayed every other slice's cut, and the
whole registry was one restartable unit. This module makes the slice the
CONCURRENCY and RESTART domain too:

- :class:`RendezvousShard` — one slice's rendezvous state machine. The
  inner manager is a plain (sliceless) ``ElasticTrainingRendezvousManager``
  with its OWN lock and its own partition in the state snapshot; a slice's
  protocol traffic (join / comm-world / waiting / reap) never touches
  another shard's lock. A shard can be wedged (chaos ``hang:shard:S``) and
  restarted alone (``kill:shard:S``) — rebuilt from its exported partition
  while every other slice keeps cutting.
- :class:`ShardedRendezvousManager` — the thin router the servicer talks
  to. Drop-in for ``ElasticTrainingRendezvousManager`` (same surface, same
  per-slice semantics, same flight events), routing each rank's calls to
  its slice's shard via a rank→slice map. Fleet-wide coordination state
  that is NOT per-slice (peer-store donor registry, the parallelism
  planner profile + memo, the world epoch) lives at router level under a
  separate lock, gathered from shards WITHOUT nesting locks (router code
  may take one shard lock at a time; shard code never takes the router
  lock).

Sliceless jobs route everything to the FLEET shard (slice id -1), whose
inner manager runs the job's real rendezvous parameters — single-slice
behavior is byte-identical to the single-lock manager.

``bench_controlplane.py`` measures the win: joins/s and per-slice
time-to-reform against the single-lock baseline at 1k simulated ranks.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.rendezvous import (
    ElasticTrainingRendezvousManager,
    RendezvousParameters,
    plan_restore_entries,
)

FLEET_SHARD = -1


def flatten_sharded_state(state: Dict) -> Dict:
    """Downgrade a SHARDED snapshot ({"shards": {sid: partition}}) into
    the single-lock manager's flat format, so the documented
    ``rdzv_sharded=0`` escape hatch (and any pre-split master binary)
    can take over a sharded lineage instead of silently restoring an
    empty protocol state. The inverse of ``_restore_legacy``."""
    shards = state.get("shards") or {}
    fleet = shards.get(str(FLEET_SHARD), {})
    flat: Dict = {
        "round": fleet.get("round", 0),
        "latest_world": dict(fleet.get("latest_world") or {}),
        "waiting": dict(fleet.get("waiting") or {}),
        "alive": list(fleet.get("alive") or ()),
        "pending_rejoin": list(fleet.get("pending_rejoin") or ()),
        "node_ips": dict(fleet.get("node_ips") or {}),
        "draining": dict(fleet.get("draining") or {}),
        "world_epoch": int(state.get("world_epoch", 0)),
        "slices": dict(state.get("slices") or {}),
        "slice_worlds": {},
        "slice_rounds": {},
        "slice_generation": {},
        "peer_stores": dict(state.get("peer_stores") or {}),
        "known_chips": dict(state.get("known_chips") or {}),
        "model_profile": dict(state.get("model_profile") or {}),
        "chip_hbm_bytes": int(state.get("chip_hbm_bytes", 0)),
        "last_plan": state.get("last_plan"),
    }
    for sid_raw, partition in shards.items():
        sid = int(sid_raw)
        if sid == FLEET_SHARD:
            continue
        flat["slice_worlds"][sid_raw] = dict(
            partition.get("latest_world") or {})
        flat["slice_rounds"][sid_raw] = partition.get("round", 0)
        # shard round doubles as the slice generation (each cut bumps
        # both in either manager)
        flat["slice_generation"][sid_raw] = partition.get("round", 0)
        flat["waiting"].update(partition.get("waiting") or {})
        flat["alive"] = sorted(
            {int(r) for r in flat["alive"]}
            | {int(r) for r in partition.get("alive") or ()})
        flat["pending_rejoin"] = sorted(
            {int(r) for r in flat["pending_rejoin"]}
            | {int(r) for r in partition.get("pending_rejoin") or ()})
        flat["node_ips"].update(partition.get("node_ips") or {})
        flat["draining"].update(partition.get("draining") or {})
    return flat


class _ShardInner(ElasticTrainingRendezvousManager):
    """The per-slice state machine: a plain sliceless manager that emits
    the SLICE-labeled observability its single-lock predecessor emitted
    from its slice-mode paths (the e2e evidence — ``slice_world_cut`` /
    ``slice_world_invalidated`` events, per-slice generation gauges —
    must not change shape when the control plane shards)."""

    def __init__(self, sid: int,
                 params: Optional[RendezvousParameters] = None):
        super().__init__(params)
        self.sid = sid

    def _emit_round_obs(self, cut_info) -> None:
        if self.sid == FLEET_SHARD:
            super()._emit_round_obs(cut_info)
            return
        duration_s, round_idx, world_size, world_ranks = cut_info
        generation = round_idx + 1
        obs.get_flight_recorder().record_event(
            "slice_world_cut", rdzv=self.name, slice=self.sid,
            round=round_idx, generation=generation, world=world_ranks)
        obs.record_span(
            "rendezvous_round", duration_s,
            attrs={"rdzv": self.name, "round": round_idx,
                   "slice": self.sid, "world_size": world_size})
        registry = obs.get_registry()
        registry.counter(
            "dlrover_tpu_rendezvous_rounds_total",
            "Completed rendezvous rounds", labelnames=("rdzv",),
        ).labels(rdzv=self.name).inc()
        registry.gauge(
            "dlrover_tpu_slice_generation",
            "Per-slice generation token: bumped each time THAT slice's "
            "world re-forms (a peer slice's failure must not move it)",
            labelnames=("slice",)).labels(
                slice=str(self.sid)).set(generation)
        registry.gauge(
            "dlrover_tpu_slice_world_size",
            "Node count of the slice's latest cut world",
            labelnames=("slice",)).labels(
                slice=str(self.sid)).set(world_size)

    def _emit_invalidation_obs(self, node_rank: int,
                               invalidated_round: int) -> None:
        if self.sid == FLEET_SHARD:
            super()._emit_invalidation_obs(node_rank, invalidated_round)
            return
        obs.get_flight_recorder().record_event(
            "slice_world_invalidated", rdzv=self.name, slice=self.sid,
            dead_rank=node_rank, round=invalidated_round)
        obs.get_registry().counter(
            "dlrover_tpu_rendezvous_world_invalidations_total",
            "Cut worlds invalidated by a member death",
            labelnames=("rdzv",),
        ).labels(rdzv=self.name).inc()


class RendezvousShard:
    """One shard: the inner state machine plus the actor-style controls
    (wedge for chaos, restart-from-partition for isolation drills)."""

    def __init__(self, sid: int, params: RendezvousParameters):
        self.sid = sid
        self._params = params
        self.inner = _ShardInner(sid, params)
        self.restarts = 0
        # monotonic deadline until which every routed call stalls at the
        # router boundary (the chaos "wedged shard": its callers block,
        # its lock does NOT — other shards are provably unaffected)
        self._wedge_until = 0.0

    def wedge(self, seconds: float) -> None:
        self._wedge_until = time.monotonic() + max(0.0, seconds)
        logger.warning("rendezvous shard %d WEDGED for %.1fs",
                       self.sid, seconds)
        obs.get_flight_recorder().record_event(
            "shard_wedged", slice=self.sid, seconds=seconds)

    @property
    def wedged(self) -> bool:
        return time.monotonic() < self._wedge_until

    def enter(self) -> None:
        """Stall while wedged. Deliberately sleeps OUTSIDE every lock:
        the caller's RPC thread blocks (that is the fault being
        simulated), never the shard's state."""
        while True:
            remaining = self._wedge_until - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.05))

    def restart(self, from_state: Optional[dict] = None) -> None:
        """Kill and rebuild the shard's state machine from its partition
        (``from_state`` = the state-backend partition when the old actor
        is unexportable; default = live export). Exactly what
        ``kill:shard:S`` chaos drives — every other shard keeps serving
        throughout."""
        state = from_state if from_state is not None \
            else self.inner.export_state()
        replacement = _ShardInner(self.sid, self._params)
        replacement.restore_state(state)
        self.inner = replacement
        self._wedge_until = 0.0
        self.restarts += 1
        logger.warning("rendezvous shard %d restarted (restart #%d)",
                       self.sid, self.restarts)
        obs.get_flight_recorder().record_event(
            "shard_restarted", slice=self.sid, restarts=self.restarts)
        obs.get_registry().counter(
            "dlrover_tpu_rendezvous_shard_restarts_total",
            "Rendezvous shards killed and rebuilt from their state "
            "partition", labelnames=("slice",),
        ).labels(slice=str(self.sid)).inc()


class ShardedRendezvousManager:
    """The router. Public surface mirrors
    ``ElasticTrainingRendezvousManager`` so the servicer, the drain/
    reconnect handlers, event callbacks and the state backend are
    agnostic to which one serves."""

    name = "elastic-training"
    slice_scoped = True

    def __init__(self, params: Optional[RendezvousParameters] = None):
        self._params = params or RendezvousParameters()
        self._lock = threading.Lock()
        self._slices: Dict[int, int] = {}
        self._shards: Dict[int, RendezvousShard] = {
            FLEET_SHARD: RendezvousShard(FLEET_SHARD, self._params)}
        # graftlint: ephemeral(dirty counter; the new incarnation restarts at 0)
        self._mutations = 0
        # the fleet-wide membership-loss clock: router base + the sum of
        # per-shard epochs (any shard's loss moves the fleet epoch)
        self._epoch_base = 0
        # fleet-wide coordination state (deliberately NOT in any shard:
        # restore plans and parallelism plans span slices)
        self._peer_stores: Dict[int, Dict] = {}
        self._known_chips: Dict[int, int] = {}
        self._model_profile: Dict[str, float] = {}
        self._chip_hbm_bytes = 0
        self._last_plan: Optional[Dict] = None
        self._last_plan_inputs: Optional[Tuple] = None
        # graftlint: ephemeral(re-pushed via push_axis_discounts)
        self._axis_discounts: Dict[str, float] = {}

    # -- routing ----------------------------------------------------------
    def _slice_params(self) -> RendezvousParameters:
        """Per-slice shards: a slice cuts when every alive member joined
        (or the grace expires) — min 1, no node_unit rounding (a slice
        cuts whole; that is the failure-domain contract)."""
        return RendezvousParameters(
            min_nodes=1, max_nodes=self._params.max_nodes,
            wait_new_node_s=self._params.wait_new_node_s, node_unit=1)

    def _ensure_shard_locked(self, sid: int) -> RendezvousShard:
        """(lock held)"""
        shard = self._shards.get(sid)
        if shard is None:
            shard = RendezvousShard(sid, self._slice_params())
            self._shards[sid] = shard
            self._mutations += 1
        return shard

    def _shard_for(self, node_rank: int) -> RendezvousShard:
        with self._lock:
            sid = self._slices.get(node_rank, FLEET_SHARD)
            return self._ensure_shard_locked(sid)

    def shard(self, sid: int) -> Optional[RendezvousShard]:
        with self._lock:
            return self._shards.get(sid)

    def _all_shards(self) -> List[RendezvousShard]:
        with self._lock:
            return list(self._shards.values())

    def _slice_shards(self) -> Dict[int, RendezvousShard]:
        with self._lock:
            return {sid: shard for sid, shard in self._shards.items()
                    if sid != FLEET_SHARD}

    # -- shard lifecycle (chaos + isolation drills) -----------------------
    def restart_shard(self, sid: int,
                      from_state: Optional[dict] = None) -> bool:
        shard = self.shard(sid)
        if shard is None:
            logger.warning("restart_shard: no shard %d", sid)
            return False
        shard.restart(from_state)
        with self._lock:
            self._mutations += 1
        return True

    def wedge_shard(self, sid: int, seconds: float) -> bool:
        shard = self.shard(sid)
        if shard is None:
            return False
        shard.wedge(seconds)
        return True

    def shards_info(self) -> Dict[int, Dict]:
        """Topology snapshot for tools/diagnose.py + the flight dump."""
        info: Dict[int, Dict] = {}
        for shard in self._all_shards():
            world = shard.inner.latest_world
            info[shard.sid] = {
                "round": shard.inner.rdzv_round,
                "world": sorted(world),
                "alive": sorted(shard.inner.alive_nodes),
                "restarts": shard.restarts,
                "wedged": shard.wedged,
            }
        return info

    # -- membership --------------------------------------------------------
    def update_rdzv_params(self, min_nodes: int, max_nodes: int,
                           wait_new_node_s: float = 30.0,
                           node_unit: int = 1) -> None:
        with self._lock:
            self._params = RendezvousParameters(
                min_nodes, max_nodes, wait_new_node_s, node_unit)
        for shard in self._all_shards():
            if shard.sid == FLEET_SHARD:
                shard.inner.update_rdzv_params(
                    min_nodes, max_nodes, wait_new_node_s, node_unit)
            else:
                shard.inner.update_rdzv_params(
                    1, max_nodes, wait_new_node_s, 1)

    @property
    def mutation_count(self) -> int:
        total = sum(s.inner.mutation_count for s in self._all_shards())
        with self._lock:
            return total + self._mutations

    @property
    def alive_nodes(self) -> set:
        alive: set = set()
        for shard in self._all_shards():
            alive |= shard.inner.alive_nodes
        return alive

    def add_alive_node(self, node_rank: int) -> None:
        self._shard_for(node_rank).inner.add_alive_node(node_rank)

    def remove_alive_node(self, node_rank: int,
                          graceful: bool = False) -> None:
        self._shard_for(node_rank).inner.remove_alive_node(
            node_rank, graceful=graceful)
        with self._lock:
            # the host's staged state goes with the host; the epoch ride
            # on the shard's own bump (inner.remove_alive_node)
            if self._peer_stores.pop(node_rank, None) is not None:
                self._mutations += 1

    def touch(self, node_rank: int) -> None:
        if node_rank < 0:
            return
        self._shard_for(node_rank).inner.touch(node_rank)

    def reap_dead_nodes(self, timeout_s: float) -> None:
        for shard in self._all_shards():
            before = shard.inner.alive_nodes
            shard.inner.reap_dead_nodes(timeout_s)
            reaped = before - shard.inner.alive_nodes
            if reaped:
                with self._lock:
                    for rank in reaped:
                        if self._peer_stores.pop(rank, None) is not None:
                            self._mutations += 1

    # -- slice registry ----------------------------------------------------
    def record_slice(self, node_rank: int, slice_id: int) -> None:
        if slice_id < 0:
            return
        with self._lock:
            if self._slices.get(node_rank) != slice_id:
                self._slices[node_rank] = slice_id
                self._mutations += 1
            self._ensure_shard_locked(slice_id)

    def slice_of(self, node_rank: int) -> int:
        with self._lock:
            return self._slices.get(node_rank, -1)

    @property
    def slice_map(self) -> Dict[int, int]:
        with self._lock:
            return dict(self._slices)

    def slice_members(self, slice_id: int) -> List[int]:
        with self._lock:
            return sorted(r for r, s in self._slices.items()
                          if s == slice_id)

    def slice_status(self) -> Dict:
        with self._lock:
            slices_map = dict(self._slices)
            shards = {sid: shard for sid, shard in self._shards.items()
                      if sid != FLEET_SHARD}
        epoch = self.world_epoch
        sids = sorted(set(slices_map.values()))
        slices: Dict[str, Dict] = {}
        for sid in sids:
            members = sorted(r for r, s in slices_map.items()
                             if s == sid)
            shard = shards.get(sid)
            world = shard.inner.latest_world if shard else {}
            draining = shard.inner.draining if shard else {}
            slices[str(sid)] = {
                "formed": bool(world),
                "ranks": sorted(world) if world else members,
                "generation": shard.inner.rdzv_round if shard else 0,
                "draining": any(r in draining for r in members),
            }
        return {"total": len(sids), "slices": slices, "epoch": epoch}

    def world_for(self, node_rank: int) -> Dict[int, int]:
        return self._shard_for(node_rank).inner.latest_world

    def round_for(self, node_rank: int) -> int:
        return self._shard_for(node_rank).inner.rdzv_round - 1

    # -- preemption drain --------------------------------------------------
    def mark_draining(self, node_rank: int, deadline: float
                      ) -> Dict[int, int]:
        return self._shard_for(node_rank).inner.mark_draining(
            node_rank, deadline)

    def complete_drain(self, node_rank: int) -> bool:
        result = self._shard_for(node_rank).inner.complete_drain(
            node_rank)
        with self._lock:
            if self._peer_stores.pop(node_rank, None) is not None:
                self._mutations += 1
        return result

    @property
    def draining(self) -> Dict[int, float]:
        merged: Dict[int, float] = {}
        for shard in self._all_shards():
            merged.update(shard.inner.draining)
        return merged

    # -- peer-to-peer restore ----------------------------------------------
    @property
    def world_epoch(self) -> int:
        total = sum(s.inner.world_epoch for s in self._all_shards())
        with self._lock:
            return self._epoch_base + total

    def register_peer_store(self, node_rank: int, addr: str, step: int,
                            keys, total_bytes: int = 0,
                            slice_id: int = -1) -> None:
        self.record_slice(node_rank, slice_id)
        with self._lock:
            if step < 0 or not keys:
                if self._peer_stores.pop(node_rank, None) is not None:
                    self._mutations += 1
                return
            self._peer_stores[node_rank] = {
                "addr": addr, "step": int(step), "keys": list(keys),
                "bytes": int(total_bytes), "ts": time.time(),
            }
            self._mutations += 1

    @property
    def peer_stores(self) -> Dict[int, Dict]:
        with self._lock:
            return {rank: dict(s)
                    for rank, s in self._peer_stores.items()}

    def compute_restore_plan(self, node_rank: int,
                             stripe: bool = False) -> Dict:
        # gather the shard-owned facts first — the router must never
        # hold its own lock while taking a shard's
        alive = self.alive_nodes
        draining = self.draining
        epoch = self.world_epoch
        with self._lock:
            stores = {
                rank: dict(store)
                for rank, store in self._peer_stores.items()
                if rank in alive and rank not in draining
            }
            slices = dict(self._slices)
        plan = plan_restore_entries(stores, node_rank, slices,
                                    stripe=stripe)
        plan["epoch"] = epoch
        if stripe:
            plan["mode"] = "stripe"
        return plan

    # -- online parallelism re-planning ------------------------------------
    def set_model_profile(self, param_count: int = 0,
                          param_bytes: int = 0,
                          flops_per_token: float = 0.0,
                          peak_flops_per_chip: float = 0.0,
                          seq_len: int = 0,
                          global_batch: int = 0,
                          tensor_divisor: int = 0,
                          fsdp_divisor: int = 0) -> None:
        updates = {"param_count": param_count,
                   "param_bytes": param_bytes,
                   "flops_per_token": flops_per_token,
                   "peak_flops_per_chip": peak_flops_per_chip,
                   "seq_len": seq_len, "global_batch": global_batch,
                   "tensor_divisor": tensor_divisor,
                   "fsdp_divisor": fsdp_divisor}
        with self._lock:
            for key, value in updates.items():
                if value and value > 0:
                    if self._model_profile.get(key) != value:
                        self._model_profile[key] = value
                        self._mutations += 1

    def set_chip_hbm(self, hbm_bytes: int) -> None:
        with self._lock:
            if hbm_bytes > 0 and self._chip_hbm_bytes != int(hbm_bytes):
                self._chip_hbm_bytes = int(hbm_bytes)
                self._mutations += 1

    def set_axis_discounts(self, discounts: Dict[str, float]) -> None:
        """Calibration-learned per-axis efficiency corrections (see the
        single-lock manager's docstring): plan-scoring input, part of
        the memo key, deliberately not a snapshot trigger."""
        with self._lock:
            self._axis_discounts = {str(k): float(v)
                                    for k, v in (discounts or {}).items()
                                    if v and v > 0}

    def _gather_plan_world(self) -> Dict[int, int]:
        """The world the next plan must cover (sharded analogue of the
        manager's ``_plan_world_locked``): per-shard cut worlds +
        waiting lists, the remembered chips of survivors mid-re-join,
        minus the dead and the draining. Shard locks are taken one at a
        time, never under the router lock."""
        worlds: Dict[int, int] = {}
        waiting: Dict[int, int] = {}
        alive: set = set()
        draining: set = set()
        for shard in self._all_shards():
            state = shard.inner.export_protocol_view()
            worlds.update(state["world"])
            waiting.update(state["waiting"])
            alive |= state["alive"]
            draining |= set(state["draining"])
        with self._lock:
            chips: Dict[int, int] = dict(self._known_chips)
        chips.update(worlds)
        chips.update(waiting)
        return {rank: int(n) for rank, n in chips.items()
                if rank in alive and rank not in draining}

    def compute_shard_plan(self, node_rank: int) -> Tuple[Dict, bool]:
        from dlrover_tpu.parallel import planner

        world = self._gather_plan_world()
        rank_shard = self._shard_for(node_rank)
        # the rank's scope stamps the plan: its shard's round doubles as
        # the generation token (each cut bumps both, exactly like the
        # single-lock manager's slice generation)
        generation = rank_shard.inner.rdzv_round
        round_ = rank_shard.inner.rdzv_round
        has_cut = any(s.inner.rdzv_round > 0 for s in self._all_shards())
        epoch = self.world_epoch
        with self._lock:
            slices = (len({self._slices.get(r, -1) for r in world})
                      if self._slices and world else 1)
            profile = planner.ModelProfile(
                param_count=int(self._model_profile.get(
                    "param_count", 0)),
                param_bytes=int(self._model_profile.get(
                    "param_bytes", 0)),
                flops_per_token=float(self._model_profile.get(
                    "flops_per_token", 0.0)),
                peak_flops_per_chip=float(self._model_profile.get(
                    "peak_flops_per_chip", 0.0)),
                seq_len=int(self._model_profile.get("seq_len", 0)),
                global_batch=int(self._model_profile.get(
                    "global_batch", 0)),
                hbm_bytes_per_chip=self._chip_hbm_bytes,
                tensor_divisor=int(self._model_profile.get(
                    "tensor_divisor", 0)),
                fsdp_divisor=int(self._model_profile.get(
                    "fsdp_divisor", 0)),
            )
            discounts = dict(self._axis_discounts)
            inputs = (tuple(sorted(world.items())), profile,
                      max(1, slices), generation, epoch, round_,
                      tuple(sorted(discounts.items())))
            if (self._last_plan is not None
                    and inputs == self._last_plan_inputs):
                return dict(self._last_plan), False
            plan = planner.plan_parallelism(
                world, profile, slices=max(1, slices),
                prev_plan=self._last_plan, generation=generation,
                epoch=epoch, round_=round_,
                axis_discounts=discounts or None)
            self._last_plan_inputs = inputs
            equivalent = planner.plans_equivalent(self._last_plan, plan)
            changed = (self._last_plan is not None and has_cut
                       and not equivalent)
            prev = None
            if not equivalent:
                prev = self._last_plan
                self._last_plan = plan
                self._mutations += 1
        if changed and prev is not None:
            obs.get_flight_recorder().record_event(
                "replan_stamped", rdzv=self.name,
                world_size=plan.get("world_size"),
                devices=plan.get("total_devices"),
                mesh=plan.get("mesh"), prev_mesh=prev.get("mesh"),
                global_batch=plan.get("global_batch"),
                batch_adjusted=plan.get("batch_adjusted"),
                resharded=plan.get("resharded"),
                generation=plan.get("generation"),
                epoch=plan.get("epoch"))
        return plan, changed

    @property
    def last_shard_plan(self) -> Optional[Dict]:
        with self._lock:
            return dict(self._last_plan) if self._last_plan else None

    # -- agent-facing protocol ---------------------------------------------
    def join_rendezvous(self, node_rank: int, local_world_size: int,
                        node_ip: str = "", slice_id: int = -1) -> int:
        with self._lock:
            if (slice_id >= 0
                    and self._slices.get(node_rank) != slice_id):
                self._slices[node_rank] = slice_id
                self._mutations += 1
            sid = self._slices.get(node_rank, FLEET_SHARD)
            shard = self._ensure_shard_locked(sid)
            self._known_chips[node_rank] = local_world_size
        shard.enter()
        return shard.inner.join_rendezvous(node_rank, local_world_size,
                                           node_ip)

    def leave_waiting(self, node_rank: int) -> None:
        self._shard_for(node_rank).inner.leave_waiting(node_rank)

    def get_comm_world(self, node_rank: int
                       ) -> Tuple[int, int, Dict[int, int]]:
        shard = self._shard_for(node_rank)
        shard.enter()
        rdzv_round, group, world = shard.inner.get_comm_world(node_rank)
        if shard.sid != FLEET_SHARD:
            group = shard.sid
        return rdzv_round, group, world

    def num_nodes_waiting(self, node_rank: int = -1) -> int:
        shard = self._shard_for(node_rank)
        shard.enter()
        return shard.inner.num_nodes_waiting(node_rank)

    @property
    def latest_world(self) -> Dict[int, int]:
        merged: Dict[int, int] = {}
        for shard in self._all_shards():
            merged.update(shard.inner.latest_world)
        return merged

    @property
    def rdzv_round(self) -> int:
        with self._lock:
            fleet = self._shards[FLEET_SHARD]
        return fleet.inner.rdzv_round

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        """Per-shard partitions cut independently (each under its own
        lock) — a shard's partition is internally consistent; cross-
        shard skew within one export is bounded by the export itself
        and resolved by the next mutation's snapshot."""
        all_shards = self._all_shards()
        shards_state = {str(shard.sid): shard.inner.export_state()
                        for shard in all_shards}
        restarts = {str(shard.sid): shard.restarts
                    for shard in all_shards if shard.restarts}
        epoch = self.world_epoch
        with self._lock:
            return {
                "sharded": 1,
                "shards": shards_state,
                "slices": {str(r): s for r, s in self._slices.items()},
                "world_epoch": epoch,
                "peer_stores": {
                    str(r): {"addr": s["addr"], "step": s["step"],
                             "keys": list(s["keys"]),
                             "bytes": s.get("bytes", 0)}
                    for r, s in self._peer_stores.items()
                },
                "known_chips": {str(r): n for r, n
                                in self._known_chips.items()},
                "model_profile": dict(self._model_profile),
                "chip_hbm_bytes": self._chip_hbm_bytes,
                "last_plan": (dict(self._last_plan)
                              if self._last_plan else None),
                "shard_restarts": restarts,
            }

    def restore_state(self, state: dict) -> None:
        if "shards" in state:
            self._restore_sharded(state)
        else:
            self._restore_legacy(state)

    def _restore_sharded(self, state: dict) -> None:
        now_epoch_total = 0
        shards: Dict[int, RendezvousShard] = {}
        for sid_raw, shard_state in (state.get("shards") or {}).items():
            sid = int(sid_raw)
            params = (self._params if sid == FLEET_SHARD
                      else self._slice_params())
            shard = RendezvousShard(sid, params)
            shard.inner.restore_state(shard_state)
            shard.restarts = int(
                (state.get("shard_restarts") or {}).get(sid_raw, 0))
            shards[sid] = shard
            now_epoch_total += shard.inner.world_epoch
        if FLEET_SHARD not in shards:
            shards[FLEET_SHARD] = RendezvousShard(FLEET_SHARD,
                                                  self._params)
        with self._lock:
            self._shards = shards
            self._slices = {int(r): int(s) for r, s in
                            (state.get("slices") or {}).items()}
            self._epoch_base = max(
                0, int(state.get("world_epoch", 0)) - now_epoch_total)
            self._restore_coordination_locked(state)

    def _restore_legacy(self, state: dict) -> None:
        """A snapshot written by the single-lock manager: split it into
        per-shard partitions (slice worlds/rounds → slice shards, the
        fleet fields → the fleet shard) so a sharded master — or the
        promoted standby — can take over an old lineage in place."""
        slices = {int(r): int(s) for r, s in
                  (state.get("slices") or {}).items()}
        slice_worlds = {int(sid): {int(r): int(n)
                                   for r, n in world.items()}
                        for sid, world in
                        (state.get("slice_worlds") or {}).items()}
        slice_rounds = {int(sid): int(n) for sid, n in
                        (state.get("slice_rounds") or {}).items()}
        alive = {int(r) for r in state.get("alive", ())}
        waiting = {int(r): int(n)
                   for r, n in (state.get("waiting") or {}).items()}
        pending = {int(r) for r in state.get("pending_rejoin", ())}
        node_ips = {int(r): ip
                    for r, ip in (state.get("node_ips") or {}).items()}
        draining = {int(r): float(d)
                    for r, d in (state.get("draining") or {}).items()}

        def members(sid: int) -> set:
            return {r for r, s in slices.items() if s == sid}

        shards: Dict[int, RendezvousShard] = {}
        for sid in sorted(set(slices.values())):
            group = members(sid)
            shard = RendezvousShard(sid, self._slice_params())
            shard.inner.restore_state({
                "round": slice_rounds.get(sid, 0),
                "latest_world": {str(r): n for r, n in
                                 slice_worlds.get(sid, {}).items()},
                "waiting": {str(r): n for r, n in waiting.items()
                            if r in group},
                "alive": sorted(alive & group),
                "pending_rejoin": sorted(pending & group),
                "node_ips": {str(r): ip for r, ip in node_ips.items()
                             if r in group},
                "draining": {str(r): d for r, d in draining.items()
                             if r in group},
            })
            shards[sid] = shard
        sliced_ranks = set(slices)
        fleet = RendezvousShard(FLEET_SHARD, self._params)
        fleet.inner.restore_state({
            "round": state.get("round", 0),
            "latest_world": state.get("latest_world", {}),
            "waiting": {r: n for r, n in
                        (state.get("waiting") or {}).items()
                        if int(r) not in sliced_ranks},
            "alive": [r for r in state.get("alive", ())
                      if int(r) not in sliced_ranks],
            "pending_rejoin": [r for r in state.get("pending_rejoin",
                                                    ())
                               if int(r) not in sliced_ranks],
            "node_ips": {r: ip for r, ip in
                         (state.get("node_ips") or {}).items()
                         if int(r) not in sliced_ranks},
            "draining": {r: d for r, d in
                         (state.get("draining") or {}).items()
                         if int(r) not in sliced_ranks},
        })
        shards[FLEET_SHARD] = fleet
        epoch_total = sum(s.inner.world_epoch for s in shards.values())
        with self._lock:
            self._shards = shards
            self._slices = slices
            self._epoch_base = max(
                0, int(state.get("world_epoch", 0)) - epoch_total)
            self._restore_coordination_locked(state)

    def _restore_coordination_locked(self, state: dict) -> None:
        """(lock held) The fleet-wide coordination fields shared by both
        snapshot formats."""
        now = time.time()
        self._peer_stores = {
            int(r): {"addr": s.get("addr", ""),
                     "step": int(s.get("step", -1)),
                     "keys": list(s.get("keys", ())),
                     "bytes": int(s.get("bytes", 0)),
                     "ts": now}
            for r, s in (state.get("peer_stores") or {}).items()
        }
        self._known_chips = {
            int(r): int(n) for r, n in
            (state.get("known_chips") or {}).items()}
        self._model_profile = {
            str(k): float(v) for k, v in
            (state.get("model_profile") or {}).items()}
        self._chip_hbm_bytes = int(state.get("chip_hbm_bytes", 0))
        last_plan = state.get("last_plan")
        self._last_plan = (dict(last_plan)
                           if isinstance(last_plan, dict) else None)
        self._last_plan_inputs = None
