"""In-master key/value store backing distributed bootstrap.

Capability parity: dlrover/python/master/elastic_training/kv_store_service.py
(the store behind the torch ``Store``) — here it bootstraps
``jax.distributed`` instead: agents publish the coordinator address, barrier
counters, and per-round process ranks under round-scoped key prefixes, so a
re-formed world after an elastic resize never collides with stale keys.

Unlike the reference (agents poll `get` in a loop), `wait` blocks server-side
on a condition variable with a timeout (exposed over RPC as KVWaitRequest),
so the client needs one RPC per ~20 s window instead of one per poll tick.
"""

from __future__ import annotations

import base64
import threading
import time
from typing import Dict, List


class KVStoreService:
    def __init__(self):
        self._store: Dict[str, bytes] = {}
        self._cond = threading.Condition()

    def set(self, key: str, value: bytes) -> None:
        with self._cond:
            self._store[key] = value
            self._cond.notify_all()

    def get(self, key: str) -> bytes:
        with self._cond:
            return self._store.get(key, b"")

    def add(self, key: str, amount: int) -> int:
        """Atomic integer add; missing key counts as 0."""
        with self._cond:
            current = int(self._store.get(key, b"0"))
            current += amount
            self._store[key] = str(current).encode()
            self._cond.notify_all()
            return current

    def wait(self, keys: List[str], timeout_s: float) -> bool:
        """Block until every key exists, or timeout. Returns success."""
        deadline = time.time() + timeout_s
        with self._cond:
            while True:
                if all(k in self._store for k in keys):
                    return True
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    def delete(self, key: str) -> None:
        with self._cond:
            self._store.pop(key, None)

    def clear_prefix(self, prefix: str) -> int:
        """Drop all keys under a (round-scoped) prefix; returns count."""
        with self._cond:
            stale = [k for k in self._store if k.startswith(prefix)]
            for k in stale:
                del self._store[k]
            return len(stale)

    def num_keys(self) -> int:
        with self._cond:
            return len(self._store)

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        """Values are bytes: base64 keeps the snapshot JSON-safe."""
        with self._cond:
            return {k: base64.b64encode(v).decode("ascii")
                    for k, v in self._store.items()}

    def restore_state(self, state: dict) -> None:
        with self._cond:
            self._store = {k: base64.b64decode(v)
                           for k, v in state.items()}
            # restored keys may satisfy a blocked wait (coordinator
            # bootstrap keys survive the master restart)
            self._cond.notify_all()
