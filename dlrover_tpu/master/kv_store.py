"""In-master key/value store backing distributed bootstrap AND the
per-step cross-slice coordination tier.

Capability parity: dlrover/python/master/elastic_training/kv_store_service.py
(the store behind the torch ``Store``) — here it bootstraps
``jax.distributed`` instead: agents publish the coordinator address, barrier
counters, and per-round process ranks under round-scoped key prefixes, so a
re-formed world after an elastic resize never collides with stale keys.

Unlike the reference (agents poll `get` in a loop), `wait` blocks server-side
on a condition variable with a timeout (exposed over RPC as KVWaitRequest),
so the client needs one RPC per ~20 s window instead of one per poll tick.

Hot keys (the gradient path). Since the multi-slice work the store also
carries the per-step cross-slice gradient exchange (``dcn/``) and the
rendezvous coordinator barriers (``coord/``). Those HOT prefixes get three
special behaviors:

- ``is_hot`` lets the servicer exempt them from the crash-consistency
  snapshot trigger (a full state export+fsync per training step would put
  storage in the gradient path). Durability splits by prefix: ``coord/``
  barrier mutations append to the attached
  :class:`~dlrover_tpu.master.state_backend.MutationLog`, which a
  restarted (or promoted standby) master replays over the last snapshot;
  ``dcn/`` payloads are deliberately EPHEMERAL — per-step, overwritten,
  absence reads as absence by protocol — so neither snapshots nor the
  log ever carry a gradient payload.
- ``get`` is a LOCK-FREE read: one dict lookup with no lock acquisition
  (safe under CPython's atomic dict ops — the store dict is never mutated
  in place, values are replaced wholesale), so a join storm serializing on
  the condition variable can never stall a step's ``dcn/`` read.
- Episode hygiene: hot keys carry a GENERATION in the key itself
  (``dcn/g<E>/...``, ``coord/<rdzv>/slice<S>/<round>``) and the store
  garbage-collects superseded generations on write — a stale
  previous-episode payload can neither be adopted (the key name moved on)
  nor accumulate forever. Collected keys are counted
  (``dlrover_tpu_kv_gc_keys_total``).
"""

from __future__ import annotations

import base64
import re
import threading
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import HOT_KV_PREFIXES as HOT_PREFIXES
from dlrover_tpu.common.constants import LOGGED_KV_PREFIXES

# Hot keys worth DURABILITY: the coord/ barrier keys (coordinator
# addresses agents kv_wait on — a promoted master must answer them or
# the surviving worlds' bootstrap breaks). The dcn/ payloads are
# deliberately NOT logged: they are per-step ephemeral (the next step
# overwrites them, readers treat absence as absence by protocol) and
# large (a grad payload per slice per step) — logging them would put a
# multi-MB disk write on the gradient path and grow the log unbounded
# between snapshots. Single-sourced in common/constants.py next to
# HOT_KV_PREFIXES (graftlint GL403).
LOGGED_PREFIXES = LOGGED_KV_PREFIXES

# Generation-namespaced key shapes → (group, generation). The GROUP is the
# key with its generation component removed; within one group only the
# newest ``keep_generations`` generations are retained.
#   dcn/g<E>/<rest>                 (parallel/dcn_sync.py, E = world epoch)
#   coord/<rdzv>/slice<S>/<round>   (per-slice jax coordinator barrier)
#   coord/<rdzv>/<round>[/<group>]  (sliceless / network-check barrier)
_GENERATION_PATTERNS = (
    re.compile(r"^(dcn/)g(\d+)(/.+)$"),
    re.compile(r"^(coord/[^/]+/slice[^/]+/)(\d+)((?:/.+)?)$"),
    re.compile(r"^(coord/[^/]+/)(\d+)((?:/.+)?)$"),
)


def split_generation(key: str) -> Optional[Tuple[str, int]]:
    """(group, generation) for a generation-namespaced key, else None.
    The group folds the non-generation segments back together so
    ``coord/t/3/grp0`` and ``coord/t/4/grp0`` share a group while
    ``coord/t/4/grp1`` does not."""
    for pattern in _GENERATION_PATTERNS:
        match = pattern.match(key)
        if match:
            return match.group(1) + match.group(3), int(match.group(2))
    return None


class KVStoreService:
    def __init__(self, keep_generations: int = 2):
        self._store: Dict[str, bytes] = {}
        self._cond = threading.Condition()
        # generation hygiene: group -> {generation -> [keys]} for the
        # namespaced hot keys; superseded generations are collected on
        # write, keeping the newest ``keep_generations`` (the current
        # episode plus one for in-flight readers of the one it replaced)
        self._keep_generations = max(1, keep_generations)
        self._generations: Dict[str, Dict[int, List[str]]] = {}
        # graftlint: ephemeral(gc tally; the registry counter is the durable surface)
        self.collected_total = 0
        # hot-key durability: appended per mutation instead of
        # triggering a snapshot (state_backend.MutationLog; None = off)
        # graftlint: ephemeral(re-attached by the restarting master's wiring)
        self._mutation_log = None

    # -- hot-key plumbing ------------------------------------------------
    @staticmethod
    def is_hot(key: str) -> bool:
        """Hot keys live on the gradient path: they must never trigger a
        control-plane snapshot (the servicer checks this)."""
        return key.startswith(HOT_PREFIXES)

    def attach_mutation_log(self, log) -> None:
        """Durability sink for hot mutations (replayed over the last
        snapshot by a restarted or promoted master)."""
        with self._cond:
            self._mutation_log = log

    def _log_mutation_locked(self, key: str, value: bytes) -> None:
        """(lock held) Append the RESULTING value (not the op), so
        replay is idempotent last-wins even for ``add``. Only the
        durable-worthy hot prefixes (LOGGED_PREFIXES) land in the log."""
        if (self._mutation_log is not None
                and key.startswith(LOGGED_PREFIXES)):
            self._mutation_log.append(key, value)

    def _gc_superseded_locked(self, key: str) -> int:
        """(lock held) Register ``key``'s generation and drop every key
        of generations its group has superseded. Returns the collected
        count — the CALLER increments the registry counter OUTSIDE the
        lock (registry children take their own locks and must never
        nest under a state lock)."""
        split = split_generation(key)
        if split is None:
            return 0
        group, generation = split
        gens = self._generations.setdefault(group, {})
        gens.setdefault(generation, [])
        if key not in gens[generation]:
            gens[generation].append(key)
        newest = sorted(gens)
        stale = newest[:-self._keep_generations]
        collected = 0
        for gen in stale:
            for stale_key in gens.pop(gen):
                if self._store.pop(stale_key, None) is not None:
                    collected += 1
                    self._log_mutation_locked(stale_key, b"")
        if collected:
            self.collected_total += collected
        return collected

    @staticmethod
    def _count_collected(collected: int) -> None:
        if not collected:
            return
        from dlrover_tpu import obs

        obs.get_registry().counter(
            "dlrover_tpu_kv_gc_keys_total",
            "Hot kv keys of superseded generations garbage-collected "
            "(episode hygiene: a stale previous-episode payload must "
            "never be re-adopted)").inc(collected)

    # -- the store -------------------------------------------------------
    def set(self, key: str, value: bytes) -> None:
        with self._cond:
            self._store[key] = value
            self._log_mutation_locked(key, value)
            collected = self._gc_superseded_locked(key)
            self._cond.notify_all()
        self._count_collected(collected)

    def get(self, key: str) -> bytes:
        # LOCK-FREE fast path, deliberately: a single dict lookup
        # (atomic under the GIL; writers replace values wholesale and
        # never mutate them in place, restore rebinds the whole dict),
        # so the per-step dcn/ reads can never queue behind a join
        # storm serializing on the condition variable.
        return self._store.get(key, b"")  # graftlint: disable=GL201

    def add(self, key: str, amount: int) -> int:
        """Atomic integer add; missing key counts as 0."""
        with self._cond:
            current = int(self._store.get(key, b"0"))
            current += amount
            self._store[key] = str(current).encode()
            self._log_mutation_locked(key, self._store[key])
            collected = self._gc_superseded_locked(key)
            self._cond.notify_all()
        self._count_collected(collected)
        return current

    def wait(self, keys: List[str], timeout_s: float) -> bool:
        """Block until every key exists, or timeout. Returns success."""
        deadline = time.time() + timeout_s
        with self._cond:
            while True:
                if all(k in self._store for k in keys):
                    return True
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)

    def delete(self, key: str) -> None:
        with self._cond:
            if self._store.pop(key, None) is not None:
                self._log_mutation_locked(key, b"")

    def clear_prefix(self, prefix: str) -> int:
        """Drop all keys under a (round-scoped) prefix; returns count."""
        with self._cond:
            stale = [k for k in self._store if k.startswith(prefix)]
            for k in stale:
                del self._store[k]
                self._log_mutation_locked(k, b"")
            return len(stale)

    def num_keys(self) -> int:
        with self._cond:
            return len(self._store)

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        """Values are bytes: base64 keeps the snapshot JSON-safe."""
        with self._cond:
            return {k: base64.b64encode(v).decode("ascii")
                    for k, v in self._store.items()}

    def restore_state(self, state: dict) -> None:
        with self._cond:
            self._store = {k: base64.b64decode(v)
                           for k, v in state.items()}
            # rebuild the generation index from the restored keys so
            # hygiene picks up where the dead master left off
            self._generations = {}
            for key in self._store:
                split = split_generation(key)
                if split is not None:
                    group, generation = split
                    self._generations.setdefault(
                        group, {}).setdefault(generation, []).append(key)
            # restored keys may satisfy a blocked wait (coordinator
            # bootstrap keys survive the master restart)
            self._cond.notify_all()

    def replay_mutations(self, entries) -> int:
        """Apply (key, value) pairs from a mutation log over the
        restored snapshot (value b"" = deletion). Last-wins, idempotent;
        returns the number applied."""
        applied = 0
        with self._cond:
            for key, value in entries:
                if value:
                    self._store[key] = value
                else:
                    self._store.pop(key, None)
                applied += 1
                split = split_generation(key)
                if split is not None and value:
                    group, generation = split
                    gens = self._generations.setdefault(group, {})
                    keys = gens.setdefault(generation, [])
                    if key not in keys:
                        keys.append(key)
            if applied:
                self._cond.notify_all()
        return applied
