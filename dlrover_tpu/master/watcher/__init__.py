"""Node event watchers (reference: dlrover/python/master/watcher/)."""

from dlrover_tpu.master.watcher.base import NodeEvent, NodeWatcher
from dlrover_tpu.master.watcher.local_watcher import LocalNodeWatcher

__all__ = ["NodeEvent", "NodeWatcher", "LocalNodeWatcher"]
