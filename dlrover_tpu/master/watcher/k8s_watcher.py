"""K8s pod watcher: watch stream → NodeEvents.

Capability parity: PodWatcher (dlrover/python/master/watcher/
k8s_watcher.py:130-193). Event parsing is delegated to the pure
`pod_to_fields` so it unit-tests without a cluster.
"""

from __future__ import annotations

from typing import Iterator, List

from dlrover_tpu.common.constants import NodeEventType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.watcher.base import NodeEvent, NodeWatcher
from dlrover_tpu.scheduler.kubernetes import K8sClient, pod_to_fields

_EVENT_TYPES = {
    "ADDED": NodeEventType.ADDED,
    "MODIFIED": NodeEventType.MODIFIED,
    "DELETED": NodeEventType.DELETED,
}


def _fields_to_node(fields: dict) -> Node:
    node = Node(fields["node_type"], fields["node_id"],
                rank_index=fields["rank_index"], name=fields["name"],
                status=fields["status"])
    node.exit_reason = fields["exit_reason"]
    node.host_addr = fields.get("pod_ip", "")
    return node


class K8sPodWatcher(NodeWatcher):
    def __init__(self, client: K8sClient, job_name: str):
        self._client = client
        self._selector = f"dlrover-tpu/job={job_name}"
        self._stopped = False

    def watch(self) -> Iterator[NodeEvent]:
        while not self._stopped:
            try:
                for raw in self._client.watch_pods(self._selector):
                    if self._stopped:
                        return
                    etype = _EVENT_TYPES.get(raw.get("type", ""))
                    if etype is None:
                        continue
                    fields = pod_to_fields(raw.get("object", {}))
                    if fields["node_id"] < 0:
                        continue
                    yield NodeEvent(etype, _fields_to_node(fields))
            except Exception as e:  # stream drop: relist + rewatch
                logger.warning("pod watch stream error: %s; rewatching", e)

    def list(self) -> List[Node]:
        nodes = []
        for raw in self._client.list_pods(self._selector):
            fields = pod_to_fields(raw)
            if fields["node_id"] >= 0:
                nodes.append(_fields_to_node(fields))
        return nodes

    def stop(self) -> None:
        self._stopped = True
