"""Watcher over the in-memory LocalCluster.

The local analog of PodWatcher (reference:
dlrover/python/master/watcher/k8s_watcher.py:130-193): subscribes to the
cluster's event stream and converts PodRecords to NodeEvents.
"""

from __future__ import annotations

import queue
from typing import Iterator, List, Optional

from dlrover_tpu.common.node import Node
from dlrover_tpu.master.watcher.base import NodeEvent, NodeWatcher
from dlrover_tpu.scheduler.local import LocalCluster, PodRecord


def _pod_to_node(pod: PodRecord) -> Node:
    node = Node(pod.node_type, pod.node_id, rank_index=pod.rank_index,
                name=pod.name, status=pod.status)
    node.exit_reason = pod.exit_reason
    return node


class LocalNodeWatcher(NodeWatcher):
    def __init__(self, cluster: LocalCluster, job_name: str = ""):
        self._cluster = cluster
        self._job_name = job_name
        self._queue: Optional["queue.Queue"] = None
        self._stopped = False

    def prime(self) -> None:
        if self._queue is None:
            self._queue = self._cluster.subscribe()

    def watch(self) -> Iterator[NodeEvent]:
        self.prime()
        while not self._stopped:
            try:
                event = self._queue.get(timeout=0.5)
            except queue.Empty:
                continue
            yield NodeEvent(event.event_type, _pod_to_node(event.pod))

    def list(self) -> List[Node]:
        return [_pod_to_node(p) for p in self._cluster.list_pods()]

    def stop(self) -> None:
        self._stopped = True
        if self._queue is not None:
            self._cluster.unsubscribe(self._queue)
