"""Watcher over Ray agent actors.

Capability parity: dlrover/python/master/watcher/ray_watcher.py — actor
liveness/exit mapped to the same NodeEvents the pod watcher emits, by
polling actor futures (Ray has no pod-style watch stream)."""

from __future__ import annotations

import time
from typing import Dict, Iterator, List

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.node import Node
from dlrover_tpu.master.watcher.base import NodeEvent, NodeWatcher
from dlrover_tpu.scheduler.ray import RayClient


class RayNodeWatcher(NodeWatcher):
    def __init__(self, client: RayClient, job_name: str = "",
                 poll_interval_s: float = 1.0):
        self._client = client
        self._job_name = job_name
        self._interval_s = poll_interval_s
        self._stopped = False
        self._last: Dict[str, str] = {}

    def _nodes(self) -> List[Node]:
        nodes = []
        for handle in self._client.list_actors():
            status = self._client.actor_status(handle.name)
            node = Node(handle.node_type, handle.node_id,
                        rank_index=handle.rank_index, name=handle.name,
                        status=status)
            nodes.append(node)
        return nodes

    def watch(self) -> Iterator[NodeEvent]:
        while not self._stopped:
            seen = set()
            for node in self._nodes():
                seen.add(node.name)
                previous = self._last.get(node.name)
                if previous != node.status:
                    self._last[node.name] = node.status
                    kind = "ADDED" if previous is None else "MODIFIED"
                    yield NodeEvent(kind, node)
            for name in list(self._last):
                if name not in seen:
                    node_type, _, node_id = name.rpartition("-")
                    node = Node(node_type, int(node_id), name=name,
                                status=NodeStatus.DELETED)
                    del self._last[name]
                    yield NodeEvent("DELETED", node)
            time.sleep(self._interval_s)

    def list(self) -> List[Node]:
        return self._nodes()

    def stop(self) -> None:
        self._stopped = True
