"""Watcher interface: platform events → neutral NodeEvents.

Capability parity: dlrover/python/master/watcher/base_watcher.py — the
NodeEvent carried from the platform event stream into the job manager.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import Iterator, List

from dlrover_tpu.common.node import Node


@dataclass
class NodeEvent:
    event_type: str   # NodeEventType
    node: Node


class NodeWatcher(abc.ABC):
    @abc.abstractmethod
    def watch(self) -> Iterator[NodeEvent]:
        """Blocking stream of node events."""

    @abc.abstractmethod
    def list(self) -> List[Node]:
        """Snapshot of current nodes (to reconcile missed events)."""

    def prime(self) -> None:  # pragma: no cover - default no-op
        """Open the event subscription before any nodes are launched so no
        creation event is missed (called ahead of the initial scale)."""

    def stop(self) -> None:  # pragma: no cover - default no-op
        pass
