"""Streaming dataset manager: unbounded shard stream with offset tracking.

Capability parity: dlrover/python/master/shard/streaming_dataset_manager.py
(:32) — shards arrive as the stream grows (the splitter has no fixed end);
workers fetch the next unread range, report consumed offsets, and the
checkpoint records the high-water mark + in-flight ranges so a restarted
job resumes the stream without loss or duplication. The master-state
backend (reference util/state/store_mananger.py) is the same JSON
checkpoint the batch manager uses.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Deque, Dict, Tuple

from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import Shard, Task
from dlrover_tpu.master.shard.dataset_manager import (
    DatasetShardCheckpoint,
    DoingTask,
)


class StreamingDatasetManager:
    """Shard queue over an append-only stream.

    `advance_watermark(n)` (fed by the stream source / a size poller)
    extends the readable range; shards of `shard_size` records are minted
    lazily up to the watermark.
    """

    def __init__(self, dataset_name: str, shard_size: int,
                 task_type: str = TaskType.TRAINING):
        self._dataset_name = dataset_name
        self._shard_size = shard_size
        self._task_type = task_type
        self._watermark = 0          # records known to exist
        self._next_offset = 0        # first record not yet sharded
        self._todo: Deque[Task] = deque()
        self._doing: Dict[int, DoingTask] = {}
        self._task_id = 0
        self._completed_records = 0

    @property
    def dataset_name(self) -> str:
        return self._dataset_name

    # -- stream growth -------------------------------------------------
    def advance_watermark(self, total_records: int) -> None:
        if total_records > self._watermark:
            self._watermark = total_records
            self._mint_shards()

    def _mint_shards(self) -> None:
        while self._next_offset + self._shard_size <= self._watermark:
            self._task_id += 1
            task = Task(
                task_id=self._task_id,
                task_type=self._task_type,
                dataset_name=self._dataset_name,
                shard=Shard(
                    start=self._next_offset,
                    end=self._next_offset + self._shard_size,
                ),
            )
            self._todo.append(task)
            self._next_offset += self._shard_size

    # -- worker protocol (same surface as BatchDatasetManager) -----------
    def get_task(self, worker_id: int) -> Task:
        if not self._todo:
            # stream has no end: an empty queue means WAIT, never "done"
            return Task(task_id=-1, task_type=TaskType.WAIT)
        task = self._todo.popleft()
        self._doing[task.task_id] = DoingTask(task, worker_id)
        return task

    def report_task_status(self, task_id: int, success: bool) -> bool:
        doing = self._doing.pop(task_id, None)
        if doing is None:
            return False
        if success:
            self._completed_records += (doing.task.shard.end
                                        - doing.task.shard.start)
        else:
            self._todo.appendleft(doing.task)
        return True

    def recover_worker_tasks(self, worker_id: int) -> int:
        recovered = 0
        for task_id in [tid for tid, d in self._doing.items()
                        if d.worker_id == worker_id]:
            self._todo.appendleft(self._doing.pop(task_id).task)
            recovered += 1
        if recovered:
            logger.info("streaming %s: requeued %d shard(s) of worker %d",
                        self._dataset_name, recovered, worker_id)
        return recovered

    def recover_timeout_tasks(self, timeout_s: float) -> int:
        now = time.time()
        recovered = 0
        for task_id in [tid for tid, d in self._doing.items()
                        if now - d.start_time > timeout_s]:
            self._todo.appendleft(self._doing.pop(task_id).task)
            recovered += 1
        return recovered

    def completed(self) -> bool:
        return False                 # a stream never completes by itself

    def completed_records(self) -> int:
        return self._completed_records

    def counts(self) -> Tuple[int, int]:
        return len(self._todo), len(self._doing)

    def get_epoch(self) -> int:
        return 0

    # -- checkpoint -------------------------------------------------------
    def checkpoint(self) -> DatasetShardCheckpoint:
        undone = [[t.shard.start, t.shard.end] for t in self._todo]
        undone += [[d.task.shard.start, d.task.shard.end]
                   for d in self._doing.values()]
        return DatasetShardCheckpoint(
            dataset_name=self._dataset_name,
            todo=sorted(undone),
            epoch=0,
            completed_records=self._completed_records,
            extra={"watermark": self._watermark,
                   "next_offset": self._next_offset},
        )

    def restore_checkpoint(self, ckpt: DatasetShardCheckpoint) -> None:
        self._todo.clear()
        self._doing.clear()
        for start, end in ckpt.todo:
            self._task_id += 1
            self._todo.append(Task(
                task_id=self._task_id, task_type=self._task_type,
                dataset_name=self._dataset_name,
                shard=Shard(start=start, end=end),
            ))
        self._completed_records = ckpt.completed_records
        extra = ckpt.extra or {}
        self._watermark = int(extra.get("watermark", self._watermark))
        self._next_offset = int(extra.get("next_offset",
                                          self._next_offset))
