"""Per-dataset shard-queue managers with checkpointable data position.

Capability parity: dlrover/python/master/shard/base_dataset_manager.py
(`DatasetShardCheckpoint` :60) and batch_dataset_manager.py (`get_task` :52,
`report_task_status` :102, `checkpoint` :157): a todo queue of shard tasks, a
doing map with start times for timeout recovery, and a JSON checkpoint of
undone shards so a restarted job resumes at the exact data position.
"""

from __future__ import annotations

import json
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import Shard, Task
from dlrover_tpu.master.shard.dataset_splitter import DatasetSplitter


@dataclass
class DoingTask:
    task: Task
    worker_id: int
    start_time: float = field(default_factory=time.time)


@dataclass
class DatasetShardCheckpoint:
    """JSON-serializable data position (reference: base_dataset_manager.py:60).

    Each todo entry is ``[start, end]`` or ``[start, end, indices]`` — the
    indices of a shuffled text shard must survive restore or the job would
    re-read the wrong records.
    """

    dataset_name: str
    todo: List[list]
    epoch: int
    completed_records: int = 0
    # lazy-split huge datasets: records already materialized this epoch
    sub_epoch_offset: int = 0
    # manager-specific state (e.g. the streaming watermark)
    extra: dict = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            "dataset_name": self.dataset_name,
            "todo": self.todo,
            "epoch": self.epoch,
            "completed_records": self.completed_records,
            "sub_epoch_offset": self.sub_epoch_offset,
            "extra": self.extra,
        })

    @classmethod
    def from_json(cls, content: str) -> "DatasetShardCheckpoint":
        d = json.loads(content)
        return cls(
            dataset_name=d["dataset_name"],
            todo=[list(t) for t in d["todo"]],
            epoch=d["epoch"],
            completed_records=d.get("completed_records", 0),
            sub_epoch_offset=d.get("sub_epoch_offset", 0),
            extra=d.get("extra", {}),
        )


class BatchDatasetManager:
    """Dispatch shard tasks of a batch (finite) dataset."""

    def __init__(self, task_type: str, splitter: DatasetSplitter):
        self._task_type = task_type
        self._splitter = splitter
        self.todo: Deque[Task] = deque()
        self.doing: Dict[int, DoingTask] = {}
        self._task_id_seq = 0
        self._completed_records = 0
        # graftlint: ephemeral(timeout heuristic; re-learned from completions)
        self._max_task_completed_time = 0.0
        # bumped on every mutation of snapshotted state — including
        # splitter epoch advances that yield NO task (a huge dataset's
        # final sub-epoch flip must reach a snapshot even though the
        # worker only got a WAIT/NONE answer). Gated on by the servicer
        # so idle WAIT polls don't pay for a state export.
        # graftlint: ephemeral(dirty counter; the new incarnation restarts at 0)
        self.mutation_count = 0

    @property
    def dataset_name(self) -> str:
        return self._splitter.dataset_name

    # -- dispatch ----------------------------------------------------------
    def get_task(self, worker_id: int) -> Task:
        """Pop the next todo task; refill from the splitter at epoch края."""
        if not self.todo and not self._splitter.epoch_finished():
            self._create_todo_tasks()
        if not self.todo:
            if self.doing:
                # Epoch exhausted but peers still working: tell the worker to
                # wait — its peers' shards may be requeued on failure.
                return Task(task_id=-1, task_type=TaskType.WAIT,
                            dataset_name=self.dataset_name)
            return Task(task_id=-1, task_type=TaskType.NONE,
                        dataset_name=self.dataset_name)
        task = self.todo.popleft()
        self.doing[task.task_id] = DoingTask(task, worker_id)
        self.mutation_count += 1
        return task

    def _create_todo_tasks(self) -> None:
        self.mutation_count += 1   # the splitter advanced even if no
        # shard comes back (final-epoch flip)
        self._splitter.create_shards()
        shards = self._splitter.get_shards()
        epoch = self._splitter.get_epoch()
        for shard in shards:
            self.todo.append(Task(
                task_id=self._task_id_seq,
                task_type=self._task_type,
                dataset_name=self.dataset_name,
                shard=shard,
                epoch=epoch,
            ))
            self._task_id_seq += 1
        if shards:
            logger.info("dataset %s: created %d tasks (epoch %d)",
                        self.dataset_name, len(shards), epoch)

    def has_pending(self) -> bool:
        """Dispatchable work exists now or after a splitter refill — the
        gate for speed-weighted dispatch (TaskManager): a WAIT answer
        may only defer a worker while there is something left to defer
        it FROM, so end-of-epoch polls never count against its pace."""
        return bool(self.todo) or not self._splitter.epoch_finished()

    # -- completion / failure ---------------------------------------------
    def report_task_status(self, task_id: int, success: bool
                           ) -> Tuple[bool, Optional[DoingTask]]:
        """Returns (known, doing). The popped DoingTask carries the
        assignee and start time so the caller can feed per-rank task
        latency into the worker-speed ledger. Failed tasks are requeued
        at the front."""
        doing = self.doing.pop(task_id, None)
        if doing is None:
            return False, None
        self.mutation_count += 1
        if success:
            elapsed = time.time() - doing.start_time
            self._max_task_completed_time = max(
                self._max_task_completed_time, elapsed
            )
            shard = doing.task.shard
            self._completed_records += shard.end - shard.start
        else:
            self.todo.appendleft(doing.task)
        return True, doing

    def recover_worker_tasks(self, worker_id: int) -> int:
        """Requeue every doing task of a dead worker (reference:
        TaskRescheduleCallback event_callback.py:105)."""
        stale = [tid for tid, d in self.doing.items()
                 if d.worker_id == worker_id]
        for tid in stale:
            self.todo.appendleft(self.doing.pop(tid).task)
        if stale:
            self.mutation_count += 1
        return len(stale)

    def recover_timeout_tasks(self, timeout_s: float) -> int:
        now = time.time()
        stale = [tid for tid, d in self.doing.items()
                 if now - d.start_time > timeout_s]
        for tid in stale:
            doing = self.doing.pop(tid)
            logger.warning("task %d of worker %d timed out; requeueing",
                           tid, doing.worker_id)
            self.todo.appendleft(doing.task)
        if stale:
            self.mutation_count += 1
        return len(stale)

    def completed(self) -> bool:
        return (self._splitter.epoch_finished() and not self.todo
                and not self.doing)

    @property
    def completed_records(self) -> int:
        return self._completed_records

    def counts(self) -> Tuple[int, int]:
        return len(self.todo), len(self.doing)

    def get_epoch(self) -> int:
        return self._splitter.get_epoch()

    # -- data-position checkpoint -----------------------------------------
    def checkpoint(self) -> DatasetShardCheckpoint:
        """Snapshot undone shards: todo + doing (doing counts as undone —
        the worker may die before completing it)."""
        def entry(shard: Shard) -> list:
            if shard.indices is not None:
                return [shard.start, shard.end, shard.indices]
            return [shard.start, shard.end]

        todo = [entry(t.shard) for t in self.todo]
        todo += [entry(d.task.shard) for d in self.doing.values()]
        return DatasetShardCheckpoint(
            dataset_name=self.dataset_name,
            todo=todo,
            epoch=self._splitter.get_epoch(),
            completed_records=self._completed_records,
            sub_epoch_offset=getattr(self._splitter, "_sub_epoch_offset", 0),
        )

    # -- crash-consistent state (master/state_backend.py) -----------------
    # Unlike the worker-facing JSON checkpoint above (which folds doing
    # into todo — a restarted JOB must re-do in-flight shards), the master
    # snapshot keeps todo and doing distinct WITH task ids and owners: a
    # restarted MASTER must neither re-dispatch a shard a live worker is
    # still computing (double assignment) nor forget it (loss), and the
    # worker's eventual TaskResult must still match by task_id.

    @staticmethod
    def _shard_entry(shard: Shard) -> list:
        if shard.indices is not None:
            return [shard.start, shard.end, shard.indices]
        return [shard.start, shard.end]

    @staticmethod
    def _shard_from_entry(entry: list) -> Shard:
        return Shard(start=entry[0], end=entry[1],
                     indices=entry[2] if len(entry) > 2 else None)

    def export_state(self) -> dict:
        def task_entry(task: Task) -> dict:
            return {"id": task.task_id, "epoch": task.epoch,
                    "shard": self._shard_entry(task.shard)}

        return {
            "task_type": self._task_type,
            "task_id_seq": self._task_id_seq,
            "completed_records": self._completed_records,
            "epoch": self._splitter.get_epoch(),
            "sub_epoch_offset": getattr(self._splitter,
                                        "_sub_epoch_offset", 0),
            "todo": [task_entry(t) for t in self.todo],
            "doing": [
                {**task_entry(d.task), "worker_id": d.worker_id,
                 "start_time": d.start_time}
                for d in self.doing.values()
            ],
        }

    def restore_state(self, state: dict) -> None:
        def task_from(entry: dict) -> Task:
            return Task(
                task_id=int(entry["id"]),
                task_type=self._task_type,
                dataset_name=self.dataset_name,
                shard=self._shard_from_entry(entry["shard"]),
                epoch=int(entry.get("epoch", 0)),
            )

        # the exported task_type wins over the constructor's: a dataset
        # re-registered (new_dataset) before the snapshot restored must
        # not flip restored tasks back to the registration default
        self._task_type = str(state.get("task_type", self._task_type))
        self._task_id_seq = int(state.get("task_id_seq", 0))
        self._completed_records = int(state.get("completed_records", 0))
        self._splitter.epoch = int(state.get("epoch", 0))
        if hasattr(self._splitter, "_sub_epoch_offset"):
            self._splitter._sub_epoch_offset = int(
                state.get("sub_epoch_offset", 0))
        self.todo = deque(task_from(e) for e in state.get("todo", ()))
        # in-flight tasks get a fresh timeout clock: charging the master's
        # outage against task_timeout_s would requeue (and double-assign)
        # shards their workers are still legitimately computing
        now = time.time()
        self.doing = {
            int(e["id"]): DoingTask(task_from(e), int(e["worker_id"]),
                                    start_time=now)
            for e in state.get("doing", ())
        }

    def restore_checkpoint(self, ckpt: DatasetShardCheckpoint) -> None:
        """Rebuild the todo queue from a checkpoint, discarding in-memory
        state (reference: batch_dataset_manager.py restore path)."""
        self.todo.clear()
        self.doing.clear()
        self._splitter.epoch = ckpt.epoch
        if hasattr(self._splitter, "_sub_epoch_offset"):
            self._splitter._sub_epoch_offset = ckpt.sub_epoch_offset
        self._completed_records = ckpt.completed_records
        for item in ckpt.todo:
            start, end = item[0], item[1]
            indices = item[2] if len(item) > 2 else None
            self.todo.append(Task(
                task_id=self._task_id_seq,
                task_type=self._task_type,
                dataset_name=self.dataset_name,
                shard=Shard(start=start, end=end, indices=indices),
                epoch=ckpt.epoch,
            ))
            self._task_id_seq += 1
