"""Dataset splitting into index-range shards.

Capability parity: dlrover/python/master/shard/dataset_splitter.py —
`TableDatasetSplitter` (:144, range-only shards), `TextDatasetSplitter`
(:257, shards carry shuffled record indices), huge-dataset sub-epoch splitting
(`_split_epoch_for_huge_dataset` :181), and the `new_dataset_splitter`
factory (:325).
"""

from __future__ import annotations

import random
from abc import ABC, abstractmethod
from typing import List, Optional

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import Shard

# Above this many shards in one epoch, split the epoch lazily in chunks.
_HUGE_SHARD_COUNT = 102_400


class DatasetSplitter(ABC):
    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int):
        if shard_size <= 0:
            raise ValueError(f"shard_size must be positive, got {shard_size}")
        self.dataset_name = dataset_name
        self.dataset_size = dataset_size
        self.shard_size = shard_size
        self._num_epochs = num_epochs
        self.epoch = 0

    @abstractmethod
    def create_shards(self) -> None:
        """Materialize shards for the next (sub-)epoch."""

    @abstractmethod
    def get_shards(self) -> List[Shard]:
        ...

    def epoch_finished(self) -> bool:
        return self.epoch >= self._num_epochs

    def get_epoch(self) -> int:
        return self.epoch


class TableDatasetSplitter(DatasetSplitter):
    """Shards are pure [start, end) ranges over a record-addressable store
    (reference: TableDatasetSplitter dataset_splitter.py:144)."""

    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int = 1, shuffle: bool = False,
                 max_shard_count: int = _HUGE_SHARD_COUNT,
                 seed: Optional[int] = None):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle
        self._shards: List[Shard] = []
        self._max_shard_count = max_shard_count
        self._rng = random.Random(seed)
        self._huge = (dataset_size // shard_size) > max_shard_count
        self._sub_epoch_offset = 0

    def get_shards(self) -> List[Shard]:
        return self._shards

    def create_shards(self) -> None:
        if self._huge:
            self._create_sub_epoch_shards()
        else:
            self._shards = self._range_shards(0, self.dataset_size)
            if self._shuffle:
                self._rng.shuffle(self._shards)
            self.epoch += 1

    def _range_shards(self, begin: int, end: int) -> List[Shard]:
        shards = []
        for start in range(begin, end, self.shard_size):
            shards.append(
                Shard(start=start, end=min(start + self.shard_size, end))
            )
        return shards

    def _create_sub_epoch_shards(self) -> None:
        """Huge datasets: materialize one chunk of shards at a time so the
        master's memory stays bounded (reference:
        _split_epoch_for_huge_dataset :181)."""
        chunk_records = self._max_shard_count * self.shard_size
        start = self._sub_epoch_offset
        if start >= self.dataset_size:
            self.epoch += 1
            self._sub_epoch_offset = 0
            start = 0
            if self.epoch_finished():
                self._shards = []
                return
        end = min(start + chunk_records, self.dataset_size)
        self._shards = self._range_shards(start, end)
        if self._shuffle:
            self._rng.shuffle(self._shards)
        self._sub_epoch_offset = end
        if self.epoch == 0 and start == 0:
            logger.info(
                "dataset %s is huge: %d records split per %d-shard sub-epoch",
                self.dataset_name, self.dataset_size, self._max_shard_count,
            )


class TextDatasetSplitter(DatasetSplitter):
    """Shards carry explicit (optionally shuffled) record indices (reference:
    TextDatasetSplitter dataset_splitter.py:257)."""

    def __init__(self, dataset_name: str, dataset_size: int, shard_size: int,
                 num_epochs: int = 1, shuffle: bool = False,
                 seed: Optional[int] = None):
        super().__init__(dataset_name, dataset_size, shard_size, num_epochs)
        self._shuffle = shuffle
        self._shards: List[Shard] = []
        self._rng = random.Random(seed)

    def get_shards(self) -> List[Shard]:
        return self._shards

    def create_shards(self) -> None:
        indices = list(range(self.dataset_size))
        if self._shuffle:
            self._rng.shuffle(indices)
        shards = []
        for start in range(0, self.dataset_size, self.shard_size):
            end = min(start + self.shard_size, self.dataset_size)
            shards.append(
                Shard(start=start, end=end, indices=indices[start:end])
            )
        self._shards = shards
        self.epoch += 1


def new_dataset_splitter(
    storage_type: str,
    dataset_name: str,
    dataset_size: int,
    shard_size: int,
    num_epochs: int = 1,
    shuffle: bool = False,
    seed: Optional[int] = None,
) -> DatasetSplitter:
    """Factory (reference: new_dataset_splitter dataset_splitter.py:325)."""
    if storage_type == "table":
        return TableDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle,
            seed=seed,
        )
    if storage_type in ("text", ""):
        return TextDatasetSplitter(
            dataset_name, dataset_size, shard_size, num_epochs, shuffle,
            seed=seed,
        )
    raise ValueError(f"unknown storage_type: {storage_type!r}")
