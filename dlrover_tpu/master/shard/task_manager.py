"""TaskManager: dynamic data sharding front door on the master.

Capability parity: dlrover/python/master/shard/task_manager.py:37 — owns one
dataset manager per registered dataset, dispatches shard tasks to whichever
worker asks (faster workers naturally get more data), recovers tasks of dead
workers and timed-out tasks, and exposes the data-position checkpoint.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional, Tuple

from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import TaskType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.messages import DatasetShardParams, Task
from dlrover_tpu.master.shard.dataset_manager import (
    BatchDatasetManager,
    DatasetShardCheckpoint,
)
from dlrover_tpu.master.shard.dataset_splitter import new_dataset_splitter


class TaskManager:
    def __init__(self):
        self._lock = threading.Lock()
        self._datasets: Dict[str, BatchDatasetManager] = {}
        # registration params, kept verbatim so a restarted master can
        # rebuild each dataset's splitter (master/state_backend.py)
        self._params: Dict[str, DatasetShardParams] = {}
        self.speed_monitor = None   # wired by the job master
        # speed-weighted dispatch (ctx.dispatch_speed_weighted):
        # (dataset, worker) -> [served, polls] stride counters.
        # Deliberately NOT exported — snapshotting poll counts would
        # persist dispatch *rhythm*, not data position.
        # graftlint: ephemeral(pace is re-learned from fresh speed evidence after a failover; data position lives in the datasets)
        self._dispatch_counters: Dict[Tuple[str, int], list] = {}

    @property
    def mutation_count(self) -> int:
        """Aggregate mutation counter over every dataset (+ the set of
        registrations itself): the servicer snapshots a TaskRequest only
        when this moved — idle WAIT polls export nothing."""
        with self._lock:
            return len(self._datasets) + sum(
                d.mutation_count for d in self._datasets.values())

    # -- dataset registration ---------------------------------------------
    def new_dataset(self, params: DatasetShardParams) -> None:
        with self._lock:
            if params.dataset_name in self._datasets:
                return  # idempotent: restarted workers re-register
            splitter = new_dataset_splitter(
                params.storage_type,
                params.dataset_name,
                params.dataset_size,
                params.shard_size,
                params.num_epochs,
                params.shuffle,
            )
            self._datasets[params.dataset_name] = BatchDatasetManager(
                params.task_type, splitter
            )
            self._params[params.dataset_name] = params
            logger.info("registered dataset %s: size=%d shard=%d epochs=%d",
                        params.dataset_name, params.dataset_size,
                        params.shard_size, params.num_epochs)

    def get_dataset(self, name: str) -> Optional[BatchDatasetManager]:
        with self._lock:
            return self._datasets.get(name)

    # -- dispatch ----------------------------------------------------------
    def get_dataset_task(self, worker_id: int, dataset_name: str) -> Task:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return Task(task_id=-1, dataset_name=dataset_name)
            if (Context.singleton().dispatch_speed_weighted
                    and self._defer_for_speed(worker_id, dataset)):
                return Task(task_id=-1, task_type=TaskType.WAIT,
                            dataset_name=dataset_name)
            return dataset.get_task(worker_id)

    def _defer_for_speed(self, worker_id: int, dataset) -> bool:
        """(lock held) Deterministic stride deferral: rank r is served
        iff served < polls x weight, with weight = its relative speed
        (SpeedMonitor.relative_speeds) clamped to
        [ctx.dispatch_weight_floor, 1.0]. Faster workers keep weight 1.0
        and are never deferred; a 3x-slow rank at the default 0.25 floor
        sees at most 3 consecutive WAITs, so progress is guaranteed and
        epoch coverage stays exactly-once (a deferral never pops a
        task, it only delays the pop). Polls count only while the
        dataset still has dispatchable work — end-of-epoch WAIT/NONE
        answers must not skew a rank's pace."""
        if self.speed_monitor is None or not dataset.has_pending():
            return False
        scores = self.speed_monitor.relative_speeds()
        score = scores.get(worker_id)
        if score is None or len(scores) < 2:
            return False   # no evidence, or no pack to pace against
        weight = max(Context.singleton().dispatch_weight_floor,
                     min(1.0, score))
        counter = self._dispatch_counters.setdefault(
            (dataset.dataset_name, worker_id), [0, 0])
        counter[1] += 1
        if counter[0] < counter[1] * weight:
            counter[0] += 1
            return False
        return True

    def report_dataset_task(self, dataset_name: str, task_id: int,
                            success: bool) -> bool:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            if dataset is None:
                return False
            known, doing = dataset.report_task_status(task_id, success)
            if (known and success and doing is not None
                    and self.speed_monitor is not None):
                # per-rank task latency feeds the worker-speed ledger
                # even before any step report carries timing, so
                # speed-weighted dispatch is not blind through the
                # data-only warmup
                shard = doing.task.shard
                self.speed_monitor.collect_task_latency(
                    doing.worker_id,
                    time.time() - doing.start_time,
                    (shard.end - shard.start) if shard else 0,
                )
            return known

    # -- recovery ----------------------------------------------------------
    def recover_tasks(self, worker_id: int) -> None:
        """A worker died: requeue all its doing tasks (reference:
        task_manager.py recover_tasks + TaskRescheduleCallback)."""
        with self._lock:
            for dataset in self._datasets.values():
                n = dataset.recover_worker_tasks(worker_id)
                if n:
                    logger.info("requeued %d tasks of dead worker %d (%s)",
                                n, worker_id, dataset.dataset_name)
            # its dispatch pace dies with it: a replacement rank must
            # not inherit the dead worker's stride position
            self._dispatch_counters = {
                k: v for k, v in self._dispatch_counters.items()
                if k[1] != worker_id
            }

    def recover_timeout_tasks(self) -> None:
        timeout = Context.singleton().task_timeout_s
        with self._lock:
            for dataset in self._datasets.values():
                dataset.recover_timeout_tasks(timeout)

    def start_timeout_recovery(self, interval_s: float = 60.0
                               ) -> threading.Thread:
        def loop():
            while True:
                time.sleep(interval_s)
                self.recover_timeout_tasks()

        thread = threading.Thread(target=loop, daemon=True,
                                  name="task-timeout-recovery")
        thread.start()
        return thread

    # -- status ------------------------------------------------------------
    def finished(self) -> bool:
        """All registered datasets exhausted (and at least one exists)."""
        with self._lock:
            return bool(self._datasets) and all(
                d.completed() for d in self._datasets.values()
            )

    def counts(self, dataset_name: str) -> Tuple[int, int]:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            return dataset.counts() if dataset else (0, 0)

    def get_epoch(self, dataset_name: str) -> int:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            return dataset.get_epoch() if dataset else 0

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        with self._lock:
            return {
                "datasets": {
                    name: {
                        "params": dataclasses.asdict(self._params[name]),
                        "progress": mgr.export_state(),
                    }
                    for name, mgr in self._datasets.items()
                    if name in self._params
                }
            }

    def restore_state(self, state: dict) -> None:
        """Rebuild every dataset (splitter from its registration params,
        progress from the manager snapshot). Registration stays
        idempotent afterwards: a restarted worker re-registering the
        dataset hits the existing new_dataset no-op path."""
        for name, entry in state.get("datasets", {}).items():
            params = DatasetShardParams(**entry["params"])
            self.new_dataset(params)
            with self._lock:
                mgr = self._datasets.get(name)
            if mgr is not None:
                mgr.restore_state(entry.get("progress", {}))

    # -- data-position checkpoint -----------------------------------------
    def checkpoint_dataset(self, dataset_name: str
                           ) -> Optional[DatasetShardCheckpoint]:
        with self._lock:
            dataset = self._datasets.get(dataset_name)
            return dataset.checkpoint() if dataset else None

    def restore_dataset_checkpoint(self, content: str) -> bool:
        try:
            ckpt = DatasetShardCheckpoint.from_json(content)
        except (ValueError, KeyError, TypeError):
            # a worker restoring a checkpoint written before any dataset
            # was registered (or a corrupted payload) must not traceback
            # in the master's log — the report RPC just answers False
            return False
        with self._lock:
            dataset = self._datasets.get(ckpt.dataset_name)
            if dataset is None:
                return False
            dataset.restore_checkpoint(ckpt)
            return True
