"""MasterServicer: dispatch the 2-RPC protocol onto master components.

Capability parity: dlrover/python/master/servicer.py:62-581 — a single
service with `get(Message)` and `report(Message)`; the servicer dispatches on
the payload dataclass type. Thin by design: every decision lives in the
component (rendezvous manager, task manager, KV store, …), the servicer only
routes.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import grpc

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.diagnosis.manager import DiagnosisManager
from dlrover_tpu.master.kv_store import KVStoreService
from dlrover_tpu.master.rendezvous import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
    RendezvousManager,
)
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.master.speed_monitor import SpeedMonitor
from dlrover_tpu.master.sync_service import ElasticPsService, SyncService

# report() payloads that mutate snapshotted control-plane state (the
# early-return branches — join/reconnect/kv-add — sink inline). The
# per-step/heartbeat/telemetry hot paths are intentionally absent, and
# KeyValuePair sinks inline ONLY for cold keys: hot-prefix (dcn/,
# coord/) sets are the gradient path — they ride the mutation log
# instead of triggering a full snapshot per training step.
_MUTATING_REPORTS = (
    msg.DatasetShardParams,
    msg.TaskResult,
    msg.LeaveRendezvousRequest,
    msg.NetworkStatusReport,
    msg.NodeFailureReport,
    msg.NodeAddressReport,   # writes node-addr/<rank> into the kv store
    msg.ShardCheckpoint,
    msg.ScaleRequest,
    msg.ModelInfo,
    msg.PeerStoreReport,     # donor registry feeds restore plans
)


class MasterServicer:
    def __init__(
        self,
        task_manager: Optional[TaskManager] = None,
        rdzv_managers: Optional[Dict[str, RendezvousManager]] = None,
        kv_store: Optional[KVStoreService] = None,
        speed_monitor: Optional[SpeedMonitor] = None,
        sync_service: Optional[SyncService] = None,
        elastic_ps_service: Optional[ElasticPsService] = None,
        job_manager=None,
        metric_collector=None,
        diagnosis_manager=None,
        goodput_ledger=None,
        tsdb=None,
        plan_calibration=None,
        steptrace=None,
        fleet_controller=None,
    ):
        self.task_manager = task_manager or TaskManager()
        self.rdzv_managers: Dict[str, RendezvousManager] = rdzv_managers or {
            RendezvousName.TRAINING: ElasticTrainingRendezvousManager(),
            RendezvousName.NETWORK_CHECK: NetworkCheckRendezvousManager(),
        }
        self.kv_store = kv_store or KVStoreService()
        self.speed_monitor = speed_monitor or SpeedMonitor()
        self.sync_service = sync_service or SyncService()
        self.elastic_ps_service = elastic_ps_service or ElasticPsService()
        self.job_manager = job_manager  # optional: node lifecycle owner
        self.metric_collector = metric_collector  # optional: stats sink
        # optional: the diagnosis engine (master/diagnosis/) — fed from
        # step/resource reports, drained by agent action polls
        self.diagnosis_manager = diagnosis_manager
        # optional: the goodput ledger (obs/goodput.py) — fed from step
        # reports, telemetry spans and drain/failure handlers
        self.goodput_ledger = goodput_ledger
        # optional: the fleet time-series store (obs/tsdb.py) — fed
        # per-rank device truth from step reports here; job-level
        # gauges ride the collector thread (JobMaster)
        self.tsdb = tsdb
        # optional: planner calibration (parallel/calibration.py) —
        # stamped plans register predictions, step reports register
        # measurements, learned discounts push back into the planner
        self.plan_calibration = plan_calibration
        # optional: the step-trace assembler (master/steptrace.py) —
        # fed batched per-step records from telemetry reports, queried
        # by tools/steptrace.py + top.py
        self.steptrace = steptrace
        # optional: the goodput-optimal fleet controller
        # (brain/fleet_controller.py) — queried by tools through the
        # AutoscaleStatusRequest RPC; its loop runs on its own thread
        self.fleet_controller = fleet_controller
        self._pushed_discounts: Dict[str, float] = {}
        # the tuned config is read on RPC threads and merged from the
        # auto-scaler thread: every access goes through _paral_lock or
        # merge's read-modify-write can lose a concurrently reported
        # config (and publish a stale version number)
        self._paral_lock = threading.Lock()
        self._paral_config = msg.ParallelConfig()
        self._start_time = time.time()
        # crash-consistency hook (wired by JobMaster): called after any
        # request that may have mutated control-plane state, so every
        # mutation lands in a durable snapshot before the next one
        self.state_sink: Optional[callable] = None
        # master generation token (bumped per restart over one state
        # lineage); 0 = no state backend, tokens disabled
        self.generation = 0
        # step-driven chaos for the master itself (kill:master:0@step):
        # wired by JobMaster, fed from worker GlobalStepReports
        self.master_chaos = None
        # the coordination tier's address ("" = not split out): rides
        # join/reconnect results so clients route hot KV traffic off the
        # control tier (master/coord_service.py)
        self.coord_addr = ""
        # telemetry rides a bounded drop-oldest queue: a span storm
        # degrades observability, never liveness
        from dlrover_tpu.master.coord_service import TelemetryIngestQueue

        self.telemetry_queue = TelemetryIngestQueue(
            self._process_telemetry,
            maxlen=Context.singleton().telemetry_queue_size)

    # ------------------------------------------------------------------
    # raw byte endpoints (wired into comm.build_server)
    # ------------------------------------------------------------------
    def get_bytes(self, payload: bytes,
                  context: Optional[grpc.ServicerContext] = None) -> bytes:
        try:
            request = msg.deserialize_message(payload)
            response = self.get(request)
        except Exception:
            logger.exception("get failed (payload %d bytes)", len(payload))
            response = msg.Response(success=False, reason="internal error")
        return msg.serialize_message(response)

    def report_bytes(self, payload: bytes,
                     context: Optional[grpc.ServicerContext] = None) -> bytes:
        try:
            request = msg.deserialize_message(payload)
            response = self.report(request)
        except Exception:
            logger.exception("report failed (payload %d bytes)", len(payload))
            response = msg.Response(success=False, reason="internal error")
        return msg.serialize_message(response)

    # ------------------------------------------------------------------
    # typed dispatch
    # ------------------------------------------------------------------
    def get(self, request: msg.Message) -> msg.Message:
        if isinstance(request, msg.TaskRequest):
            # counter (not task emptiness) gates the snapshot: a final-
            # epoch splitter flip mutates state yet answers WAIT/NONE
            before = self.task_manager.mutation_count
            task = self.task_manager.get_dataset_task(
                request.worker_id, request.dataset_name
            )
            if self.task_manager.mutation_count != before:
                self._sink_state()
            return task
        if isinstance(request, msg.CommWorldRequest):
            mgr = self.rdzv_managers[request.rdzv_name]
            # polls vastly outnumber mutations: only a poll that actually
            # changed rendezvous state (cut a round) pays for a snapshot
            before = mgr.mutation_count
            rdzv_round, group, world = mgr.get_comm_world(request.node_id)
            if mgr.mutation_count != before:
                self._sink_state()
            if (self.goodput_ledger is not None and world
                    and request.rdzv_name == RendezvousName.TRAINING):
                # a cut training world: the ledger opens an incarnation
                # per new round (idempotent for repeat polls)
                self.goodput_ledger.observe_world(rdzv_round, len(world))
            return msg.CommWorld(rdzv_name=request.rdzv_name,
                                 round=rdzv_round, group=group, world=world)
        if isinstance(request, msg.WaitingNodeNumRequest):
            mgr = self.rdzv_managers[request.rdzv_name]
            # the steady-state poll every live agent makes: liveness
            # touch + dead-member reaping ride on it, so agent death is
            # detected even with no node manager (standalone masters)
            mgr.touch(request.node_id)
            before = mgr.mutation_count
            mgr.reap_dead_nodes(
                Context.singleton().dead_node_timeout_s)
            if mgr.mutation_count != before:
                self._sink_state()   # a dead member was reaped
                self._evict_departed(mgr)
            # node_id carries the rank on this RPC (master_client):
            # slice mode scopes the membership-change signal to the
            # polling rank's slice
            return msg.WaitingNodeNum(
                waiting_num=mgr.num_nodes_waiting(request.node_id))
        if isinstance(request, msg.DiagnosisActionRequest):
            actions = []
            if self.diagnosis_manager is not None:
                actions = self.diagnosis_manager.poll_actions(
                    request.node_rank if request.node_rank >= 0
                    else request.node_id)
            return msg.DiagnosisActions(
                actions_json=DiagnosisManager.actions_to_json(actions))
        if isinstance(request, msg.DiagnosisReportRequest):
            reports = []
            if self.diagnosis_manager is not None:
                reports = self.diagnosis_manager.reports(request.limit)
            return msg.DiagnosisReports(
                reports_json=DiagnosisManager.reports_to_json(reports))
        if isinstance(request, msg.GoodputRequest):
            import json

            if self.goodput_ledger is None:
                return msg.GoodputReport(report_json="")
            return msg.GoodputReport(report_json=json.dumps(
                self.goodput_ledger.snapshot(
                    window_s=request.window_s)))
        if isinstance(request, msg.AutoscaleStatusRequest):
            import json

            if self.fleet_controller is None:
                return msg.AutoscaleStatus(status_json="")
            return msg.AutoscaleStatus(status_json=json.dumps(
                self.fleet_controller.status()))
        if isinstance(request, msg.TimeSeriesQuery):
            import json

            if self.tsdb is None:
                return msg.TimeSeriesResult(result_json="")
            payload = self.tsdb.query_payload(
                name=request.name,
                labels=dict(request.labels) or None,
                window_s=request.window_s,
                resolution_s=request.resolution_s)
            return msg.TimeSeriesResult(
                result_json=json.dumps(payload))
        if isinstance(request, msg.ClockProbe):
            # answered inline with no locks and no state: the RTT the
            # client measures around this IS its uncertainty bound —
            # queueing here would inflate every stamped error bar
            return msg.ClockProbeResult(server_ts=time.time())
        if isinstance(request, msg.StepTraceRequest):
            import json

            if self.steptrace is None:
                return msg.StepTraceResult(result_json="")
            return msg.StepTraceResult(result_json=json.dumps(
                self.steptrace.query_payload(
                    start_step=request.start_step,
                    end_step=request.end_step,
                    last_n=request.last_n)))
        if isinstance(request, msg.PlanCalibrationRequest):
            import json

            if self.plan_calibration is None:
                return msg.PlanCalibrationReport(report_json="")
            return msg.PlanCalibrationReport(report_json=json.dumps({
                "table": self.plan_calibration.table(),
                "discounts": self.plan_calibration.axis_discounts(),
                "min_samples": self.plan_calibration.min_samples,
            }))
        if isinstance(request, msg.SliceStatusRequest):
            import json

            mgr = self.rdzv_managers.get(
                request.rdzv_name or RendezvousName.TRAINING)
            if mgr is None:
                return msg.SliceStatus(status_json="")
            status = mgr.slice_status()
            # the re-formed slice's catch-up target (dcn_sync.catch_up)
            status["fleet_step"] = (
                self.speed_monitor.completed_global_step)
            return msg.SliceStatus(status_json=json.dumps(status))
        if isinstance(request, msg.RestorePlanRequest):
            import json

            mgr = self.rdzv_managers.get(
                request.rdzv_name or RendezvousName.TRAINING)
            if mgr is None:
                return msg.RestorePlan()
            if request.epoch_only:
                # the staleness guard's commit-time check: just the
                # current world epoch, no plan computation
                return msg.RestorePlan(epoch=mgr.world_epoch)
            plan = mgr.compute_restore_plan(
                request.node_rank,
                stripe=bool(getattr(request, "stripe", False)))
            return msg.RestorePlan(
                plan_json=json.dumps(plan),
                epoch=int(plan.get("epoch", 0)),
                step=int(plan.get("step", -1)),
                found=bool(plan.get("entries")))
        if isinstance(request, msg.ShardPlanRequest):
            import json

            mgr = self.rdzv_managers.get(
                request.rdzv_name or RendezvousName.TRAINING)
            if mgr is None:
                return msg.ShardPlanResult()
            before = mgr.mutation_count
            plan, changed = mgr.compute_shard_plan(request.node_rank)
            if changed:
                self._note_replan(plan)
            self._observe_plan(plan)
            if mgr.mutation_count != before:
                self._sink_state()   # a new plan was stamped
            return msg.ShardPlanResult(
                plan_json=json.dumps(plan),
                epoch=int(plan.get("epoch", 0)),
                generation=int(plan.get("generation", 0)),
                found=bool(plan.get("mesh")))
        if isinstance(request, msg.KVGetRequest):
            return msg.KeyValuePair(key=request.key,
                                    value=self.kv_store.get(request.key))
        if isinstance(request, msg.KVWaitRequest):
            # Cap the blocking window well below typical RPC deadlines so the
            # client always receives a response, not DEADLINE_EXCEEDED.
            ok = self.kv_store.wait(request.keys,
                                    min(request.timeout_s, 20.0))
            return msg.Response(success=ok)
        if isinstance(request, msg.NetworkCheckResultRequest):
            mgr = self.rdzv_managers[RendezvousName.NETWORK_CHECK]
            fault, rounds = mgr.check_fault_node()
            stragglers = mgr.detect_stragglers()
            is_fault = request.node_id in fault
            is_straggler = request.node_id in stragglers
            return msg.NetworkCheckVerdict(
                normal=not is_fault,
                is_straggler=is_straggler,
                reason="fault" if is_fault else
                       ("straggler" if is_straggler else ""),
            )
        if isinstance(request, msg.ShardCheckpointRequest):
            ckpt = self.task_manager.checkpoint_dataset(request.dataset_name)
            return msg.ShardCheckpoint(
                dataset_name=request.dataset_name,
                content=ckpt.to_json() if ckpt else "",
            )
        if isinstance(request, msg.DatasetEpochInfo):
            return msg.DatasetEpochInfo(
                dataset_name=request.dataset_name,
                epoch=self.task_manager.get_epoch(request.dataset_name),
            )
        if isinstance(request, msg.TaskCounts):
            todo, doing = self.task_manager.counts(request.dataset_name)
            return msg.TaskCounts(dataset_name=request.dataset_name,
                                  todo=todo, doing=doing)
        if isinstance(request, msg.ParallelConfigRequest):
            with self._paral_lock:
                return self._paral_config
        if isinstance(request, msg.SyncQueryRequest):
            finished = self.sync_service.sync_finished(request.sync_name)
            return msg.Response(success=finished)
        if isinstance(request, msg.ClusterVersionRequest):
            version = self.elastic_ps_service.get_cluster_version(
                request.version_type, request.task_type, request.task_id
            )
            return msg.ClusterVersion(version=version)
        if isinstance(request, msg.JobStatusRequest):
            return self._get_job_status()
        logger.warning("get: unknown request %s", type(request).__name__)
        return msg.Response(success=False, reason="unknown request")

    def report(self, request: msg.Message) -> msg.Message:
        ok = True
        reason = ""
        if isinstance(request, msg.DatasetShardParams):
            self.task_manager.new_dataset(request)
        elif isinstance(request, msg.TaskResult):
            if not request.success and request.err_message:
                # the worker's failure detail must not die in the RPC:
                # recover_tasks requeues silently otherwise
                logger.warning("task %d of %s failed on worker %d: %s",
                               request.task_id, request.dataset_name,
                               request.worker_id,
                               request.err_message[:256])
            ok = self.task_manager.report_dataset_task(
                request.dataset_name, request.task_id, request.success
            )
        elif isinstance(request, msg.JoinRendezvousRequest):
            mgr = self.rdzv_managers[request.rdzv_name]
            # parent under the agent's span so the cross-process timeline
            # (agent rendezvous → master join → round cut) shares a trace
            slice_id = getattr(request, "slice_id", -1)
            with obs.span("rendezvous_join",
                          {"rank": request.node_rank,
                           "rdzv": request.rdzv_name,
                           "slice": slice_id},
                          parent=getattr(request, "trace", None) or None):
                rdzv_round = mgr.join_rendezvous(
                    request.node_rank, request.local_world_size,
                    request.node_ip, slice_id)
            if (slice_id >= 0
                    and request.rdzv_name == RendezvousName.TRAINING):
                # keep every slice-labeled consumer's rank→slice view
                # current (per-worker gauges, goodput states, per-slice
                # speed aggregates)
                self._push_slice_map(mgr)
            self._sink_state()
            plan_json = ""
            shard_plan_json = ""
            if request.rdzv_name == RendezvousName.TRAINING:
                # the restore plan rides the join result: which
                # surviving donor serves each staged shard this rank
                # may need (checkpoint/peer_restore.py). Best-effort at
                # this instant — late-registering donors are picked up
                # by the worker's RestorePlanRequest re-fetch.
                import json

                plan = mgr.compute_restore_plan(request.node_rank)
                if plan.get("entries"):
                    plan_json = json.dumps(plan)
                # the parallelism plan for the world this join is
                # forming (parallel/planner.py): the same deterministic
                # mesh + batch shape for every rank of the new world,
                # so the resize resolves in ONE rendezvous round
                try:
                    before = mgr.mutation_count
                    shard_plan, changed = mgr.compute_shard_plan(
                        request.node_rank)
                    shard_plan_json = json.dumps(shard_plan)
                    if changed:
                        self._note_replan(shard_plan)
                    self._observe_plan(shard_plan)
                    if mgr.mutation_count != before:
                        self._sink_state()   # the stamped plan is state
                except Exception:  # noqa: BLE001 — the planner must
                    # never fail a join; workers fall back to their
                    # configured mesh (loud replan_fallback on their
                    # side)
                    logger.exception("shard-plan computation failed "
                                     "for rank %d", request.node_rank)
            return msg.JoinRendezvousResult(
                round=rdzv_round, generation=self.generation,
                restore_plan_json=plan_json,
                shard_plan_json=shard_plan_json,
                coord_addr=self.coord_addr)
        elif isinstance(request, msg.ReconnectRequest):
            return self._handle_reconnect(request)
        elif isinstance(request, msg.DrainReport):
            return self._handle_drain(request)
        elif isinstance(request, msg.LeaveRendezvousRequest):
            mgr = self.rdzv_managers[request.rdzv_name]
            mgr.leave_waiting(request.node_rank)
        elif isinstance(request, msg.NetworkStatusReport):
            mgr = self.rdzv_managers[RendezvousName.NETWORK_CHECK]
            mgr.report_network_status(request.node_id, request.normal,
                                      request.elapsed_time)
        elif isinstance(request, msg.KeyValuePair):
            self.kv_store.set(request.key, request.value)
            if not self.kv_store.is_hot(request.key):
                # cold keys keep write-through durability; hot ones
                # (the gradient path) ride the mutation log instead
                self._sink_state()
        elif isinstance(request, msg.KVAddRequest):
            value = self.kv_store.add(request.key, request.amount)
            if not self.kv_store.is_hot(request.key):
                self._sink_state()
            return msg.KVIntResult(value=value)
        elif isinstance(request, msg.GlobalStepReport):
            # keyed by RANK when the sender provides one: diagnosis
            # actions address agents by rank (node_id diverges from rank
            # after a relaunch), so the straggler evidence must too
            rank = (request.node_rank if request.node_rank >= 0
                    else request.node_id)
            self.speed_monitor.collect_worker_step(
                rank,
                request.step,
                step_time_s=request.step_time_s,
                data_wait_fraction=request.data_wait_fraction,
                mfu=request.mfu)
            if self.goodput_ledger is not None:
                self.goodput_ledger.observe_step_report(
                    rank, request.step,
                    step_time_s=request.step_time_s,
                    data_wait_fraction=request.data_wait_fraction,
                    mfu=request.mfu)
            degraded = int(getattr(request, "degraded_steps", 0) or 0)
            if degraded > 0:
                self._observe_degraded_steps(rank, degraded)
            self._observe_step_evidence(rank, request)
            self._touch_rendezvous(request.node_rank)
            # deliberately NOT a snapshot trigger (the per-step hot
            # path); the step high-water mark rides on the next
            # control-plane mutation's snapshot
            if self.master_chaos is not None:
                self.master_chaos.maybe_inject(request.step)
        elif isinstance(request, msg.NodeResourceStats):
            if self.job_manager is not None:
                self.job_manager.update_node_resource_usage(request)
            if self.metric_collector is not None:
                self.metric_collector.collect_node_stats(request)
            if self.diagnosis_manager is not None:
                self.diagnosis_manager.observe_resource_stats(request)
            # observed per-chip HBM totals bound the planner's
            # memory-fit term (parallel/planner.py)
            hbm_mb = max((c.hbm_total_mb for c in request.chip_stats),
                         default=0.0)
            if hbm_mb > 0:
                training = self.rdzv_managers.get(
                    RendezvousName.TRAINING)
                if training is not None:
                    training.set_chip_hbm(int(hbm_mb * (1 << 20)))
            # the ResourceMonitor's payload made scrapeable on the master
            obs.publish_node_stats(request)
        elif isinstance(request, msg.NodeHeartbeat):
            if self.job_manager is not None:
                self.job_manager.collect_heartbeat(
                    request.node_id, request.timestamp,
                    node_type=request.node_type)
            self._touch_rendezvous(request.node_rank)
        elif isinstance(request, msg.NodeFailureReport):
            logger.warning("node %d failure (level=%s, kind=%s): %s",
                           request.node_id, request.level,
                           request.exit_kind or "-",
                           request.error_data[:512])
            if self.job_manager is not None:
                self.job_manager.handle_failure_report(request)
            self.task_manager.recover_tasks(request.node_id)
            if self.diagnosis_manager is not None and request.exit_kind:
                # hang vs crash vs drain lands in the report history —
                # they demand different responses
                self.diagnosis_manager.observe_worker_exit(
                    request.node_rank if request.node_rank >= 0
                    else request.node_id,
                    request.exit_kind, detail=request.error_data[:128])
            if self.goodput_ledger is not None:
                from dlrover_tpu.common.constants import NodeExitReason

                failed_rank = (request.node_rank
                               if request.node_rank >= 0
                               else request.node_id)
                if request.exit_kind == NodeExitReason.HANG:
                    self.goodput_ledger.observe_hang(
                        failed_rank,
                        Context.singleton().hang_watchdog_s)
                elif request.exit_kind != NodeExitReason.DRAINED:
                    self.goodput_ledger.note_elasticity_event(
                        "worker_lost")
        elif isinstance(request, msg.PeerStoreReport):
            mgr = self.rdzv_managers.get(
                request.rdzv_name or RendezvousName.TRAINING)
            if mgr is not None:
                mgr.register_peer_store(
                    request.node_rank, request.addr, request.step,
                    request.keys, request.total_bytes,
                    slice_id=getattr(request, "slice_id", -1))
        elif isinstance(request, msg.NodeAddressReport):
            self.kv_store.set(f"node-addr/{request.node_rank}",
                              request.addr.encode())
        elif isinstance(request, msg.ShardCheckpoint):
            ok = self.task_manager.restore_dataset_checkpoint(request.content)
        elif isinstance(request, msg.SyncJoinRequest):
            ok = self.sync_service.join_sync(request.sync_name,
                                             request.node_id)
        elif isinstance(request, msg.SyncFinishRequest):
            ok = self.sync_service.finish_sync(request.sync_name)
        elif isinstance(request, msg.ClusterVersionRequest):
            self.elastic_ps_service.update_cluster_version(
                request.version_type, request.version,
                request.task_type, request.task_id,
            )
        elif isinstance(request, msg.ParallelConfig):
            with self._paral_lock:
                self._paral_config = request
        elif isinstance(request, msg.ScaleRequest):
            if self.job_manager is not None:
                self.job_manager.handle_scale_request(request)
            else:
                ok, reason = False, "no job manager"
        elif isinstance(request, msg.ModelInfo):
            logger.info(
                "model info: %.3gB params, flops/token=%.3g (%s), "
                "batch=%d seq=%d chips=%d",
                request.param_count / 1e9, request.flops_per_token,
                request.flops_source or "analytic",
                request.batch_size, request.seq_len, request.chips)
            if self.job_manager is not None:
                self.job_manager.collect_model_info(request)
            if self.metric_collector is not None:
                self.metric_collector.collect_model_info(request)
            # tokens/s exposition = steps/s × tokens-per-step (the
            # EFFECTIVE batch when a re-plan adjusted it)
            effective = (getattr(request, "effective_global_batch", 0)
                         or request.batch_size)
            self.speed_monitor.set_tokens_per_step(
                effective * request.seq_len,
                seq_len=request.seq_len)
            # MFU exposition = tokens/s × FLOPs/token / aggregate peak;
            # the per-chip peak is kept so a world re-plan can
            # re-anchor the denominator to the NEW chip count without
            # waiting for the next worker report
            self.speed_monitor.set_model_flops(
                request.flops_per_token,
                request.peak_flops_per_chip * max(1, request.chips),
                peak_flops_per_chip=request.peak_flops_per_chip)
            # the planner's model profile (parallel/planner.py)
            training = self.rdzv_managers.get(RendezvousName.TRAINING)
            if training is not None:
                training.set_model_profile(
                    param_count=request.param_count,
                    param_bytes=request.param_bytes,
                    flops_per_token=request.flops_per_token,
                    peak_flops_per_chip=request.peak_flops_per_chip,
                    seq_len=request.seq_len,
                    global_batch=request.batch_size,
                    tensor_divisor=getattr(request, "tensor_divisor",
                                           0),
                    fsdp_divisor=getattr(request, "fsdp_divisor", 0))
        elif isinstance(request, msg.TelemetryReport):
            # bounded queue + one drainer thread: the RPC returns after
            # one append, however large the span replay backlog is
            self.telemetry_queue.push(request)
        else:
            logger.warning("report: unknown request %s",
                           type(request).__name__)
            ok, reason = False, "unknown request"
        if isinstance(request, _MUTATING_REPORTS):
            self._sink_state()
        return msg.Response(success=ok, reason=reason)

    # ------------------------------------------------------------------
    def _handle_reconnect(self, request: msg.ReconnectRequest
                          ) -> msg.ReconnectResult:
        """An agent lost us (or our predecessor) and is re-registering.
        Its rank re-enters the alive set either way; ``world_intact``
        tells it whether the workers it kept running still form the
        master's latest world — or whether it must re-join rendezvous."""
        name = request.rdzv_name or RendezvousName.TRAINING
        mgr = self.rdzv_managers.get(name)
        if mgr is None:
            return msg.ReconnectResult(generation=self.generation)
        slice_id = getattr(request, "slice_id", -1)
        if slice_id >= 0:
            mgr.record_slice(request.node_rank, slice_id)
        mgr.add_alive_node(request.node_rank)
        # slice mode: intact means the rank's SLICE world still holds it
        # at the round it reported — a peer slice having moved on is
        # irrelevant to this agent (that is the failure domain)
        world = mgr.world_for(request.node_rank)
        latest_round = mgr.round_for(request.node_rank)
        intact = (bool(world) and request.node_rank in world
                  and request.rdzv_round == latest_round)
        restarted = (self.generation != 0
                     and request.generation != self.generation)
        logger.info(
            "agent %d reconnected (rank %d, saw generation %d, ours %d, "
            "round %d): %s", request.node_id, request.node_rank,
            request.generation, self.generation, request.rdzv_round,
            "world intact" if intact else "must re-join rendezvous")
        obs.get_flight_recorder().record_event(
            "agent_reconnect", node=request.node_id,
            rank=request.node_rank, world_intact=intact,
            master_restarted=restarted)
        obs.get_registry().counter(
            "dlrover_tpu_agent_reconnects_total",
            "Agents that re-registered after a master-lost episode",
            labelnames=("world_intact",),
        ).labels(world_intact=str(intact).lower()).inc()
        self._sink_state()
        return msg.ReconnectResult(generation=self.generation,
                                   world_intact=intact,
                                   round=latest_round,
                                   coord_addr=self.coord_addr)

    def _handle_drain(self, request: msg.DrainReport) -> msg.DrainResult:
        """The advance-notice drain protocol. phase="notice": mark the
        rank DRAINING in every rendezvous, pre-plan the post-departure
        world, and fan an urgent ``checkpoint`` action out to the
        SURVIVORS (the draining agent checkpoints its own worker
        locally). phase="complete": remove the rank now — survivors
        re-form in one round instead of waiting out the liveness
        timeout."""
        rank = (request.node_rank if request.node_rank >= 0
                else request.node_id)
        checkpoint_ranks = []
        if request.phase == "complete":
            announced = False
            if self.goodput_ledger is not None:
                # notice → departure is drain badput; the rank's
                # lifetime in the ledger ends here
                self.goodput_ledger.complete_drain(rank)
            for mgr in self.rdzv_managers.values():
                announced = mgr.complete_drain(rank) or announced
                self._evict_departed(mgr)
            logger.info("node %d drain COMPLETE (announced=%s): "
                        "survivors re-form now", rank, announced)
        else:
            # slice-scoped drain: a preemption notice for ANY rank of a
            # slice drains the SLICE as a unit — same-slice peers get
            # save-and-EXIT drain actions (their jax world dies with the
            # slice anyway), ranks outside it get the save-and-continue
            # checkpoint fan-out. Single-slice jobs keep the PR 5 shape.
            training = self.rdzv_managers.get(RendezvousName.TRAINING)
            sid = training.slice_of(rank) if training is not None else -1
            slice_peers = []
            if sid >= 0 and training is not None:
                slice_peers = [r for r in training.slice_members(sid)
                               if r != rank]
            draining_unit = [rank] + slice_peers
            if self.goodput_ledger is not None:
                for member in draining_unit:
                    self.goodput_ledger.mark_draining(member,
                                                      request.deadline)
            planned = {}
            for name, mgr in self.rdzv_managers.items():
                unit = (draining_unit
                        if name == RendezvousName.TRAINING else [rank])
                for member in unit:
                    world = mgr.mark_draining(member, request.deadline)
                if name == RendezvousName.TRAINING:
                    planned = world
            # the checkpoint fan-out targets the FLEET's survivors: in
            # slice mode the planned world above is the (now empty)
            # victim slice's — the ranks worth saving are every ALIVE
            # rank outside the draining unit (alive membership, not cut
            # worlds: a notice can land before the first world forms)
            if sid >= 0 and training is not None:
                survivors = sorted(training.alive_nodes
                                   - set(draining_unit))
            else:
                survivors = sorted(r for r in planned
                                   if r not in draining_unit)
            drain_ranks: list = []
            if self.diagnosis_manager is not None:
                self.diagnosis_manager.observe_drain_notice(
                    rank, request.deadline, request.reason,
                    slice_id=sid)
                if slice_peers:
                    drain_ranks = self.diagnosis_manager.request_drain(
                        slice_peers, request.deadline,
                        reason=f"slice {sid} draining (notice on rank "
                               f"{rank}): {request.reason}")
                checkpoint_ranks = (
                    self.diagnosis_manager.request_checkpoint(
                        survivors, request.deadline,
                        reason=f"peer rank {rank} draining: "
                               f"{request.reason}"))
            obs.get_flight_recorder().record_event(
                "node_draining", rank=rank, deadline=request.deadline,
                reason=request.reason[:256], slice=sid,
                planned_world=sorted(planned),
                drain_ranks=drain_ranks,
                checkpoint_ranks=checkpoint_ranks)
        obs.get_registry().counter(
            "dlrover_tpu_drains_total",
            "Drain protocol messages by phase",
            labelnames=("phase",)).labels(phase=request.phase).inc()
        # dlrover_tpu_draining_nodes is published by the rendezvous
        # manager itself: every mutation path (including blown-deadline
        # reaps and re-join cancels that never pass through this RPC)
        # keeps the gauge honest
        self._sink_state()
        return msg.DrainResult(success=True,
                               checkpoint_ranks=checkpoint_ranks)

    # ------------------------------------------------------------------
    def _observe_step_evidence(self, rank: int,
                               request: msg.GlobalStepReport) -> None:
        """Per-rank history + calibration feeds off one step report
        (the hot path: appends only, no snapshot, no RPC fan-out).
        The device-truth HBM watermark lands in the diagnosis node
        stats (HbmPressureRule's preferred signal) and the time-series
        store; timing evidence lands in the calibration table, whose
        learned axis discounts push back into the planner whenever
        they change."""
        hbm_peak = float(getattr(request, "hbm_peak_bytes", 0.0) or 0.0)
        peak_mb = hbm_peak / (1 << 20) if hbm_peak > 0 else -1.0
        if peak_mb >= 0.0 and self.diagnosis_manager is not None:
            self.diagnosis_manager.observe_step_watermark(rank, peak_mb)
        if self.tsdb is not None:
            node = {"node": str(rank)}
            # dlrover_tpu_training_global_step is deliberately NOT
            # ingested here: the collector samples the SpeedMonitor's
            # fleet-truth gauge into that (unlabeled) series — a
            # per-rank ingest on the same key would interleave
            # straggler steps with the fleet step (one feed per series)
            if request.step_time_s > 0:
                self.tsdb.ingest(
                    "dlrover_tpu_worker_step_time_seconds",
                    request.step_time_s, node)
            if request.mfu >= 0:
                self.tsdb.ingest("dlrover_tpu_worker_mfu",
                                 request.mfu, node)
            if peak_mb >= 0.0:
                self.tsdb.ingest("dlrover_tpu_worker_hbm_peak_mb",
                                 peak_mb, node)
        if self.plan_calibration is not None \
                and request.step_time_s > 0:
            self.plan_calibration.observe_step(
                request.step_time_s, mfu=request.mfu,
                plan_generation=int(getattr(
                    request, "plan_generation", -1)))
            # the learned-discount recompute + push deliberately does
            # NOT happen here: this is the per-report hot path, and
            # the medians only move as samples accumulate — the
            # diagnosis loop's cadence recomputes and pushes
            # (DiagnosisManager.discount_sink)

    def push_axis_discounts(self, discounts: Dict[str, float]) -> None:
        """Feed learned calibration discounts into planner scoring,
        deduped on change. The single owner of the push state — the
        restore path (JobMaster) reuses it so the dedup field never
        has a second writer."""
        if discounts == self._pushed_discounts:
            return
        self._pushed_discounts = discounts
        training = self.rdzv_managers.get(RendezvousName.TRAINING)
        if training is not None and \
                hasattr(training, "set_axis_discounts"):
            training.set_axis_discounts(discounts)

    # ------------------------------------------------------------------
    def _note_replan(self, plan: Dict) -> None:
        """A REAL re-plan was stamped (the execution shape changed):
        attribute the next world re-formation to it in the goodput
        ledger, and re-anchor the speed monitor's denominators — the
        tokens/s and MFU gauges must not report the new world against
        the old chip count or the old (possibly adjusted) batch."""
        if self.goodput_ledger is not None:
            self.goodput_ledger.note_elasticity_event("replan")
        tokens_per_step = (int(plan.get("global_batch", 0) or 0)
                           * int(self.speed_monitor.seq_len_hint or 0))
        self.speed_monitor.reanchor_plan(
            chips=int(plan.get("total_devices", 0) or 0),
            tokens_per_step=tokens_per_step)
        obs.get_registry().counter(
            "dlrover_tpu_replans_total",
            "Parallelism re-plans stamped (the execution shape "
            "changed at a resize)").inc()

    # ------------------------------------------------------------------
    def _observe_plan(self, plan: Dict) -> None:
        """Register a stamped plan's prediction with the calibration
        table (idempotent per signature; re-stamps for late joiners
        continue the same measurement series)."""
        if self.plan_calibration is None:
            return
        try:
            self.plan_calibration.observe_plan(plan)
        except Exception:  # noqa: BLE001 — calibration is advisory
            logger.exception("plan calibration observe failed")

    # ------------------------------------------------------------------
    def _push_slice_map(self, mgr) -> None:
        """Fan the rank→slice view to every slice-labeled consumer."""
        slice_map = mgr.slice_map
        if not slice_map:
            return
        self.speed_monitor.set_slice_map(slice_map)
        if self.diagnosis_manager is not None:
            self.diagnosis_manager.set_slice_map(slice_map)
        if self.goodput_ledger is not None:
            self.goodput_ledger.set_slice_map(slice_map)

    # ------------------------------------------------------------------
    def _observe_degraded_steps(self, rank: int, count: int) -> None:
        """A slice reported degraded steps (gradient mean renormalized
        while a peer slice was absent): master-side counter labeled by
        the REPORTING slice + the goodput ledger's per-rank tally."""
        mgr = self.rdzv_managers.get(RendezvousName.TRAINING)
        sid = mgr.slice_of(rank) if mgr is not None else -1
        obs.get_registry().counter(
            "dlrover_tpu_slice_degraded_steps_total",
            "Steps a slice took with the gradient mean renormalized "
            "over present slices (a peer slice was absent)",
            labelnames=("slice",)).labels(slice=str(sid)).inc(count)
        if self.goodput_ledger is not None:
            self.goodput_ledger.observe_degraded_steps(rank, count)

    # ------------------------------------------------------------------
    def _sink_state(self) -> None:
        """Post-mutation crash-consistency hook; snapshot failures must
        never fail the RPC that triggered them."""
        sink = self.state_sink
        if sink is None:
            return
        try:
            sink()
        except Exception:  # noqa: BLE001 — durability is best-effort
            logger.exception("control-plane state snapshot failed")

    # ------------------------------------------------------------------
    def _process_telemetry(self, report: msg.TelemetryReport) -> None:
        """Replay a node's metric samples on the master registry and feed
        its spans into the master flight recorder + span histogram (runs
        on the ingest queue's drainer thread)."""
        import json

        registry = obs.get_registry()
        for sample in report.samples:
            if not sample.name:
                continue
            labels = dict(sample.labels)
            labels.setdefault("node", str(report.node_id))
            try:
                names = tuple(sorted(labels))
                if sample.kind == "counter":
                    registry.counter(sample.name, labelnames=names).labels(
                        **labels).inc(sample.value)
                elif sample.kind == "histogram":
                    registry.histogram(sample.name,
                                       labelnames=names).labels(
                        **labels).observe(sample.value)
                else:
                    registry.gauge(sample.name, labelnames=names).labels(
                        **labels).set(sample.value)
            except (TypeError, ValueError) as e:
                logger.warning("telemetry sample %s dropped: %s",
                               sample.name, e)
        if report.spans_json:
            try:
                spans = json.loads(report.spans_json)
            except json.JSONDecodeError:
                logger.warning("telemetry spans from node %d undecodable",
                               report.node_id)
                spans = None
            if isinstance(spans, list):
                obs.record_remote_spans(spans, registry)
                if self.goodput_ledger is not None:
                    for record in spans:
                        if isinstance(record, dict):
                            self.goodput_ledger.observe_span(
                                record, rank=report.node_rank)
        if getattr(report, "steptrace_json", "") and \
                self.steptrace is not None:
            try:
                records = json.loads(report.steptrace_json)
            except json.JSONDecodeError:
                logger.warning(
                    "steptrace batch from node %d undecodable",
                    report.node_id)
                return
            if isinstance(records, list):
                self.steptrace.ingest(records,
                                      node_rank=report.node_rank)

    # ------------------------------------------------------------------
    def _evict_departed(self, mgr) -> None:
        """After a reap mutated membership: per-worker speed evidence,
        straggler gauges and queued actions for the reaped ranks must go
        with them (ISSUE: never rank dead ranks)."""
        live = mgr.alive_nodes
        self.speed_monitor.evict_departed(live)
        if self.diagnosis_manager is not None:
            self.diagnosis_manager.evict_workers(live)
        if self.goodput_ledger is not None:
            self.goodput_ledger.evict(live)
        if self.steptrace is not None:
            self.steptrace.evict_departed(live)

    # ------------------------------------------------------------------
    def _touch_rendezvous(self, node_rank: int) -> None:
        """Liveness must not depend on the num_nodes_waiting poll alone:
        heartbeats and step reports carry the sender's RANK (the key the
        rendezvous alive-set uses; node_id diverges from rank after a
        relaunch), so they count as liveness too. Otherwise a user-raised
        --monitor-interval near dead_node_timeout_s gets healthy agents
        reaped mid-training. touch() ignores rank < 0 (legacy senders)."""
        for mgr in self.rdzv_managers.values():
            mgr.touch(node_rank)

    # ------------------------------------------------------------------
    def _get_job_status(self) -> msg.JobStatus:
        from dlrover_tpu.common.constants import JobStage

        if self.job_manager is not None:
            return msg.JobStatus(stage=self.job_manager.job_stage())
        stage = (JobStage.SUCCEEDED if self.task_manager.finished()
                 else JobStage.RUNNING)
        return msg.JobStatus(stage=stage)

    def update_paral_config(self, config: msg.ParallelConfig) -> None:
        with self._paral_lock:
            self._paral_config = config

    def merge_paral_config(self, **fields) -> msg.ParallelConfig:
        """Merge tuned knobs into the current config, bumping its version
        (partial updates must not clobber other tuned fields or publish a
        stale version number).  The read-modify-write holds _paral_lock:
        the auto-scaler merges on its own thread while RPC threads
        report/replace the config."""
        import dataclasses

        with self._paral_lock:
            current = self._paral_config
            merged = dataclasses.replace(
                current, version=current.version + 1,
                **{k: v for k, v in fields.items() if v})
            self._paral_config = merged
        return merged
