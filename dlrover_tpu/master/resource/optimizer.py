"""Resource plan model + optimizer interface.

Capability parity: dlrover/python/master/resource/optimizer.py
(ResourcePlan :48, ResourceOptimizer :134) — stage-based plans
(job-create / node-initial / running / OOM recovery) produced per job,
consumed by the auto-scaler. TPU framing: node resources are host CPU/mem
plus attached chips; "hot PS CPU" maps to hot-host (input-bound) detection.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.node import NodeGroupResource


class OptimizeStage:
    JOB_CREATE = "job-create"       # cold start: before any node runs
    NODE_INITIAL = "node-initial"   # first nodes running, little history
    RUNNING = "running"             # steady state
    OOM_RECOVERY = "oom-recovery"


@dataclass
class ResourceLimits:
    """Upper bounds from the job spec (CRD resourceLimits)."""

    max_nodes: int = 0
    max_cpu: float = 0.0
    max_memory_mb: float = 0.0
    max_chips: int = 0


@dataclass
class ResourcePlan:
    """Target group resources per node type + optional tuned runtime knobs."""

    node_group_resources: Dict[str, NodeGroupResource] = field(
        default_factory=dict)
    # Tuned worker-process knobs (forwarded as ParallelConfig).
    dataloader_batch_size: int = 0
    dataloader_workers: int = 0

    def empty(self) -> bool:
        return not self.node_group_resources

    def limit(self, limits: ResourceLimits) -> "ResourcePlan":
        for group in self.node_group_resources.values():
            if limits.max_cpu:
                group.node_resource.cpu = min(group.node_resource.cpu,
                                              limits.max_cpu)
            if limits.max_memory_mb:
                group.node_resource.memory_mb = min(
                    group.node_resource.memory_mb, limits.max_memory_mb)
            if limits.max_chips:
                group.node_resource.chips = min(group.node_resource.chips,
                                                limits.max_chips)
            if limits.max_nodes:
                group.count = min(group.count, limits.max_nodes)
        return self


class ResourceOptimizer(abc.ABC):
    """Produces plans from observed stats (reference: ResourceOptimizer
    base; implementations: PSLocalOptimizer, BrainOptimizer)."""

    @abc.abstractmethod
    def generate_plan(self, stage: str,
                      config: Optional[dict] = None) -> ResourcePlan:
        ...

    def generate_oom_recovery_plan(self, node_type: str,
                                   current_memory_mb: float) -> ResourcePlan:
        plan = ResourcePlan()
        group = NodeGroupResource()
        group.node_resource.memory_mb = current_memory_mb * 1.5
        plan.node_group_resources[node_type] = group
        return plan
