"""Single-job local resource optimizer.

Capability parity: PSLocalOptimizer (dlrover/python/master/resource/
local_optimizer.py:66) re-framed for TPU allreduce jobs:
- JOB_CREATE: cold-start plan from job config (or defaults).
- NODE_INITIAL: right-size host cpu/mem from first observed usage.
- RUNNING: pick the worker count with the best marginal throughput
  (reference `_generate_worker_resoruce` :189 uses the speed ratio), and
  detect input-bound "hot hosts" (reference `_optimize_hot_ps_cpu` :299:
  hot-PS CPU fix → here: hosts whose CPU is saturated while chips idle get
  more dataloader workers/CPU).
- OOM_RECOVERY: inherited 1.5× memory bump.
"""

from __future__ import annotations

from typing import Optional

from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.master.resource.optimizer import (
    OptimizeStage,
    ResourceOptimizer,
    ResourcePlan,
)
from dlrover_tpu.master.resource.stats_collector import RuntimeStatsCollector

# Sizing margins (reference uses 1.2-1.5 factors for cpu/mem headroom).
_CPU_HEADROOM = 1.25
_MEM_HEADROOM = 1.4
# Hot-host (input-bound) thresholds — shared with the brain's
# optimize_job_hot_host so the two detectors cannot diverge.
HOT_HOST_CPU_PCT = 90.0
IDLE_CHIP_DUTY_PCT = 50.0


class LocalResourceOptimizer(ResourceOptimizer):
    def __init__(self, stats: Optional[RuntimeStatsCollector] = None,
                 scale_unit: int = 1):
        self.stats = stats or RuntimeStatsCollector()
        # worker-count deltas must respect TPU slice granularity (hosts per
        # slice), the analog of the reference's node_unit rounding
        self._scale_unit = max(1, scale_unit)
        # counts whose marginal throughput gain failed the efficiency gate;
        # never explored again (prevents a grow/shrink oscillation)
        self._rejected_counts: set = set()

    def generate_plan(self, stage: str,
                      config: Optional[dict] = None) -> ResourcePlan:
        config = config or {}
        if stage == OptimizeStage.JOB_CREATE:
            return self._job_create_plan(config)
        if stage == OptimizeStage.NODE_INITIAL:
            return self._node_initial_plan(config)
        if stage == OptimizeStage.RUNNING:
            return self._running_plan(config)
        return ResourcePlan()

    # -- stages --------------------------------------------------------
    def _job_create_plan(self, config: dict) -> ResourcePlan:
        plan = ResourcePlan()
        count = int(config.get("worker_count", 0))
        if count:
            plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
                count=count,
                node_resource=NodeResource(
                    cpu=float(config.get("cpu", 8)),
                    memory_mb=float(config.get("memory_mb", 16384)),
                    chips=int(config.get("chips", 4)),
                    chip_type=config.get("chip_type", ""),
                ),
            )
        return plan

    def _node_initial_plan(self, config: dict) -> ResourcePlan:
        peak = self.stats.max_node_usage(NodeType.WORKER)
        plan = ResourcePlan()
        if peak["memory_mb"] <= 0:
            return plan
        current = config.get("current", NodeResource())
        group = NodeGroupResource(
            count=0,  # 0 = keep count; only resize the shape
            node_resource=NodeResource(
                cpu=max(current.cpu,
                        peak["cpu_percent"] / 100.0 * _CPU_HEADROOM
                        * max(current.cpu, 1)),
                memory_mb=peak["memory_mb"] * _MEM_HEADROOM,
                chips=current.chips,
                chip_type=current.chip_type,
            ),
        )
        plan.node_group_resources[NodeType.WORKER] = group
        return plan

    def _running_plan(self, config: dict) -> ResourcePlan:
        plan = ResourcePlan()
        speeds = self.stats.speed_by_worker_count()
        current_count = int(config.get("worker_count", 0))
        max_count = int(config.get("max_worker_count", current_count))
        if speeds and current_count:
            target = self._best_worker_count(speeds, current_count,
                                             max_count)
            if target != current_count:
                plan.node_group_resources[NodeType.WORKER] = (
                    NodeGroupResource(count=target))
        self._tune_hot_hosts(plan)
        return plan

    def _best_worker_count(self, speeds: dict, current: int,
                           max_count: int) -> int:
        """Grow while marginal scaling efficiency stays above 50%
        (reference: worker count from speed ratio,
        local_optimizer.py:189-243). Speed 0 (startup / compilation) is
        treated as "no data", never as a shrink signal — stall handling
        belongs to hang detection, not the auto-scaler."""
        base_speed = speeds.get(current, 0.0)
        if base_speed <= 0:
            return current
        smaller = current - self._scale_unit
        threshold = 1 + 0.5 * self._scale_unit / max(smaller, 1)
        if smaller in speeds and speeds[smaller] > 0:
            # we grew into `current` earlier; verify the growth paid off,
            # otherwise shrink back and blacklist this count
            if base_speed <= speeds[smaller] * threshold:
                self._rejected_counts.add(current)
                return smaller
        grown = current + self._scale_unit
        if grown > max_count or grown in self._rejected_counts:
            return current
        if grown in speeds and speeds[grown] > 0:
            gate = 1 + 0.5 * self._scale_unit / current
            if speeds[grown] > base_speed * gate:
                return grown
            self._rejected_counts.add(grown)
            return current
        # unobserved: one exploration step (a failed step is shrunk back
        # and blacklisted on the next round)
        return grown

    def _tune_hot_hosts(self, plan: ResourcePlan) -> None:
        """Input-bound host: CPU pegged while chips idle ⇒ raise dataloader
        parallelism (the TPU analog of the hot-PS CPU fix)."""
        hot = 0
        for node_id in self.stats.node_ids(NodeType.WORKER):
            sample = self.stats.latest_node_sample(NodeType.WORKER, node_id)
            if (sample and sample.cpu_percent >= HOT_HOST_CPU_PCT
                    and 0 < sample.chip_duty_cycle_pct
                    < IDLE_CHIP_DUTY_PCT):
                hot += 1
        if hot:
            logger.info("detected %d input-bound (hot) hosts", hot)
            plan.dataloader_workers = 2  # signal: double dataloader workers
