"""Resource plans, optimizers, auto-scaling
(reference: dlrover/python/master/resource/)."""

from dlrover_tpu.master.resource.optimizer import (
    ResourceLimits,
    ResourceOptimizer,
    ResourcePlan,
)
from dlrover_tpu.master.resource.local_optimizer import LocalResourceOptimizer

__all__ = [
    "ResourceLimits",
    "ResourceOptimizer",
    "ResourcePlan",
    "LocalResourceOptimizer",
]
