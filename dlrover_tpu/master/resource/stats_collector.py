"""Runtime-stats store feeding the resource optimizer.

Capability parity: the stats side of dlrover/python/master/resource/
local_optimizer.py (its sqlite-free in-memory stats) + master/stats/
training_metrics.py — rolling per-node resource samples and global
throughput samples the optimizer reads.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, List, Optional


@dataclass
class NodeSample:
    timestamp: float
    cpu_percent: float
    memory_mb: float
    chip_duty_cycle_pct: float = 0.0
    hbm_used_mb: float = 0.0


@dataclass
class SpeedSample:
    timestamp: float
    worker_count: int
    steps_per_sec: float


class RuntimeStatsCollector:
    """Rolling window of node/speed samples."""

    def __init__(self, window: int = 200):
        self._node_samples: Dict[str, Dict[int, Deque[NodeSample]]] = {}
        self._speed_samples: Deque[SpeedSample] = deque(maxlen=window)
        self._window = window
        self._lock = threading.Lock()

    def add_node_sample(self, node_type: str, node_id: int,
                        sample: NodeSample) -> None:
        with self._lock:
            by_id = self._node_samples.setdefault(node_type, {})
            samples = by_id.setdefault(
                node_id, deque(maxlen=self._window))
            samples.append(sample)

    def add_speed_sample(self, worker_count: int,
                         steps_per_sec: float) -> None:
        with self._lock:
            self._speed_samples.append(
                SpeedSample(time.time(), worker_count, steps_per_sec))

    def latest_node_sample(self, node_type: str,
                           node_id: int) -> Optional[NodeSample]:
        with self._lock:
            samples = self._node_samples.get(node_type, {}).get(node_id)
            return samples[-1] if samples else None

    def node_ids(self, node_type: str) -> List[int]:
        with self._lock:
            return list(self._node_samples.get(node_type, {}))

    def speed_by_worker_count(self) -> Dict[int, float]:
        """worker count → mean steps/sec (for scale-efficiency estimates)."""
        with self._lock:
            acc: Dict[int, List[float]] = {}
            for s in self._speed_samples:
                acc.setdefault(s.worker_count, []).append(s.steps_per_sec)
        return {k: sum(v) / len(v) for k, v in acc.items() if v}

    def max_node_usage(self, node_type: str) -> Dict[str, float]:
        """Peak cpu%/memory over all nodes of a type (sizing input)."""
        peak = {"cpu_percent": 0.0, "memory_mb": 0.0, "hbm_used_mb": 0.0}
        with self._lock:
            for samples in self._node_samples.get(node_type, {}).values():
                for s in samples:
                    peak["cpu_percent"] = max(peak["cpu_percent"],
                                              s.cpu_percent)
                    peak["memory_mb"] = max(peak["memory_mb"], s.memory_mb)
                    peak["hbm_used_mb"] = max(peak["hbm_used_mb"],
                                              s.hbm_used_mb)
        return peak
