"""Job metric collection/reporting (reference: dlrover/python/master/stats/)."""

from dlrover_tpu.master.stats.job_collector import JobMetricCollector
from dlrover_tpu.master.stats.reporter import (
    LocalStatsReporter,
    StatsReporter,
)

__all__ = ["JobMetricCollector", "StatsReporter", "LocalStatsReporter"]
