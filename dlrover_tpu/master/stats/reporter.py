"""Stats reporters: local log store or brain service.

Capability parity: dlrover/python/master/stats/reporter.py (ReporterType
LOCAL / DLROVER_BRAIN selection in dist_master.py:116-127).
"""

from __future__ import annotations

import abc
import json
import threading
import time
from typing import Any, Dict, List, Optional


class ReporterType:
    LOCAL = "local"
    BRAIN = "brain"


class StatsReporter(abc.ABC):
    @abc.abstractmethod
    def report(self, record_type: str, payload: Dict[str, Any]) -> None:
        ...

    @classmethod
    def new_reporter(cls, reporter_type: str = ReporterType.LOCAL,
                     **kwargs) -> "StatsReporter":
        if reporter_type == ReporterType.BRAIN:
            from dlrover_tpu.brain.client import BrainReporter

            return BrainReporter(**kwargs)
        return LocalStatsReporter(**kwargs)


class LocalStatsReporter(StatsReporter):
    """Keeps records in memory and (optionally) appends JSON lines to a
    file for offline analysis."""

    def __init__(self, path: Optional[str] = None):
        self._path = path
        self._records: List[Dict[str, Any]] = []
        self._lock = threading.Lock()

    def report(self, record_type: str, payload: Dict[str, Any]) -> None:
        record = {"type": record_type, "ts": time.time(), **payload}
        with self._lock:
            self._records.append(record)
            if self._path:
                with open(self._path, "a") as f:
                    f.write(json.dumps(record) + "\n")

    def records(self, record_type: Optional[str] = None
                ) -> List[Dict[str, Any]]:
        with self._lock:
            if record_type is None:
                return list(self._records)
            return [r for r in self._records if r["type"] == record_type]
