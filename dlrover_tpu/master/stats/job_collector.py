"""Job metric collector: gathers job/runtime/model stats and reports them.

Capability parity: JobMetricCollector (dlrover/python/master/stats/
job_collector.py) — job meta at start, periodic runtime stats (node usage +
global step), model info once known, job-exit record. Feeds either the
local reporter or the brain service for cluster-mode optimization.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.master.resource.stats_collector import (
    NodeSample,
    RuntimeStatsCollector,
)
from dlrover_tpu.master.stats.reporter import StatsReporter


class JobMetricCollector:
    def __init__(
        self,
        job_name: str,
        reporter: StatsReporter,
        stats: Optional[RuntimeStatsCollector] = None,
        interval_s: float = 30.0,
    ):
        self._job_name = job_name
        self._reporter = reporter
        self.stats = stats or RuntimeStatsCollector()
        self._interval_s = interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._speed_monitor = None
        self._job_manager = None
        self._model_reported = False

    def attach(self, speed_monitor=None, job_manager=None) -> None:
        # wired once during master construction, before start() spawns
        # the report loop: the loop thread only ever reads these
        self._speed_monitor = speed_monitor    # graftlint: disable=GL701
        self._job_manager = job_manager        # graftlint: disable=GL701

    # -- ingest (called from the servicer path) -------------------------
    def collect_node_stats(self, stats: msg.NodeResourceStats) -> None:
        hbm = 0.0
        # duty_cycle_pct = -1.0 means the sender could not derive one
        # (first export, no step context) — averaging the sentinel in
        # would hand the brain a fabricated "idle" reading
        known = [c.duty_cycle_pct for c in stats.chip_stats
                 if c.duty_cycle_pct >= 0.0]
        duty = sum(known) / len(known) if known else -1.0
        if stats.chip_stats:
            hbm = sum(c.hbm_used_mb for c in stats.chip_stats)
        self.stats.add_node_sample(
            stats.node_type or NodeType.WORKER, stats.node_id,
            NodeSample(
                timestamp=time.time(),
                cpu_percent=stats.cpu_percent,
                memory_mb=stats.memory_mb,
                chip_duty_cycle_pct=duty,
                hbm_used_mb=hbm,
            ),
        )

    def collect_model_info(self, info: msg.ModelInfo) -> None:
        if not self._model_reported:
            self._reporter.report("model", {
                "job": self._job_name,
                "param_count": info.param_count,
                "param_bytes": info.param_bytes,
                "flops_per_step": info.flops_per_step,
                "batch_size": info.batch_size,
                "seq_len": info.seq_len,
            })
            self._model_reported = True

    def report_job_meta(self, **meta) -> None:
        self._reporter.report("job_meta", {"job": self._job_name, **meta})

    def report_job_exit(self, stage: str, reason: str = "") -> None:
        self._reporter.report("job_exit", {
            "job": self._job_name, "stage": stage, "reason": reason,
        })

    # -- periodic runtime reporting -------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="metric-collector")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            self._reporter.report("runtime", self._runtime_payload())

    def _runtime_payload(self) -> dict:
        payload = {"job": self._job_name}
        if self._speed_monitor is not None:
            payload["global_step"] = (
                self._speed_monitor.completed_global_step)
            payload["steps_per_sec"] = self._speed_monitor.running_speed()
        if self._job_manager is not None:
            payload["running_workers"] = len(
                self._job_manager.get_running_workers())
        # Per-node aggregates so the brain's algorithms (hot-host, OOM
        # sizing) see the fields they key on.
        peak = self.stats.max_node_usage(NodeType.WORKER)
        if peak["memory_mb"]:
            payload["peak_memory_mb"] = peak["memory_mb"]
        latest = [
            s for s in (
                self.stats.latest_node_sample(NodeType.WORKER, node_id)
                for node_id in self.stats.node_ids(NodeType.WORKER))
            if s is not None
        ]
        if latest:
            payload["cpu_percent"] = max(s.cpu_percent for s in latest)
            known = [s.chip_duty_cycle_pct for s in latest
                     if s.chip_duty_cycle_pct >= 0.0]
            if known:
                # omitted entirely when no node has derived one yet —
                # the brain's hot-host rule must read "unknown", not 0%
                payload["chip_duty_cycle_pct"] = sum(known) / len(known)
        return payload
