"""Named barriers across workers.

Capability parity: dlrover/python/master/elastic_training/sync_service.py:26 —
workers join a named sync; the barrier is finished either when every expected
worker joined or when explicitly finished by a controller; workers poll the
barrier state. Used e.g. around mesh re-lowering and PS migration points.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Set


class SyncService:
    def __init__(self, expected_workers: Optional[int] = None):
        self._lock = threading.Lock()
        self._syncs: Dict[str, Set[int]] = {}
        self._finished: Set[str] = set()
        self._expected_workers = expected_workers

    def set_expected_workers(self, count: int) -> None:
        with self._lock:
            self._expected_workers = count

    def join_sync(self, sync_name: str, node_id: int) -> bool:
        with self._lock:
            members = self._syncs.setdefault(sync_name, set())
            members.add(node_id)
            if (self._expected_workers
                    and len(members) >= self._expected_workers):
                self._finished.add(sync_name)
            return True

    def sync_finished(self, sync_name: str) -> bool:
        with self._lock:
            return sync_name in self._finished

    def finish_sync(self, sync_name: str) -> bool:
        with self._lock:
            self._finished.add(sync_name)
            return True

    def remove_node(self, node_id: int) -> None:
        with self._lock:
            for members in self._syncs.values():
                members.discard(node_id)


class ElasticPsService:
    """Cluster-version arbitration for PS-style failover (reference:
    dlrover/python/master/elastic_training/elastic_ps.py:18).

    Workers hold a local version; the master holds the global version. After
    a PS-style state holder migrates, the global version bumps and workers
    reconcile (re-connect / restore) when their local version lags.
    """

    LOCAL = "local"
    GLOBAL = "global"
    RESTORED = "restored"

    def __init__(self):
        self._lock = threading.Lock()
        self._global_version = 0
        self._node_versions: Dict[str, Dict[int, int]] = {}
        self._restored_version = 0

    def inc_global_cluster_version(self) -> int:
        with self._lock:
            self._global_version += 1
            return self._global_version

    def update_cluster_version(self, version_type: str, version: int,
                               task_type: str, task_id: int) -> None:
        with self._lock:
            if version_type == self.LOCAL:
                self._node_versions.setdefault(task_type, {})[task_id] = (
                    version
                )
            elif version_type == self.GLOBAL:
                self._global_version = version
            elif version_type == self.RESTORED:
                self._restored_version = version

    def remove_node(self, task_type: str, task_id: int) -> None:
        """Drop a dead node's published local version so cluster-wide
        reconciliation checks never wait on it."""
        with self._lock:
            self._node_versions.get(task_type, {}).pop(task_id, None)

    def get_cluster_version(self, version_type: str, task_type: str,
                            task_id: int) -> int:
        with self._lock:
            if version_type == self.LOCAL:
                return self._node_versions.get(task_type, {}).get(task_id, 0)
            if version_type == self.RESTORED:
                return self._restored_version
            return self._global_version
