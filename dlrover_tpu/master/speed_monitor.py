"""Global-step speed monitoring and hang detection.

Capability parity: dlrover/python/master/monitor/speed_monitor.py:43 —
collect (timestamp, global_step) samples, compute windowed throughput,
track per-worker step reports, and flag a hang when no step progress is made
for `hang_seconds`.

Publishes through the obs metrics registry (docs/observability.md):
``dlrover_tpu_training_global_step`` / ``_steps_per_second`` /
``_tokens_per_second`` collect-time gauges and the
``dlrover_tpu_train_step_time_seconds`` histogram observed per step
report. All shared step/worker state is written from servicer threads
and read from the master watch loop + metrics scrapes — every access
goes through ``self._lock``; registry observes happen OUTSIDE the lock
(sinks must never run under it).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Set, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.config import Context


class SpeedMonitor:
    def __init__(self):
        self._lock = threading.Lock()
        ctx = Context.singleton()
        self._samples: Deque[Tuple[float, int]] = deque(
            maxlen=ctx.speed_sample_window
        )
        self._global_step = 0
        self._first_step_time: Optional[float] = None
        self._last_step_time: float = time.time()
        self._workers: Set[int] = set()
        self._worker_steps: Dict[int, int] = {}
        self._start_training_time: Optional[float] = None
        self._paused_time_s: float = 0.0
        self._tokens_per_step: int = 0
        # set at membership change: the NEXT step-report delta spans the
        # failover gap (rendezvous + recompile + restore), not step time
        self._skip_next_step_time = False
        self._publish_metrics()

    def _publish_metrics(self) -> None:
        """Collect-time gauges: scrapes read live values through the
        monitor's own locked queries (the newest monitor instance in a
        process wins the registration — matching the newest master)."""
        registry = obs.get_registry()
        registry.gauge(
            "dlrover_tpu_training_global_step",
            "Latest global step reported by any worker",
        ).set_function(lambda: self.completed_global_step)
        registry.gauge(
            "dlrover_tpu_training_steps_per_second",
            "Windowed training throughput",
        ).set_function(self.running_speed)
        registry.gauge(
            "dlrover_tpu_training_tokens_per_second",
            "Windowed throughput x tokens per step (from ModelInfo)",
        ).set_function(self.tokens_per_second)
        registry.gauge(
            "dlrover_tpu_training_running_workers",
            "Workers currently joined on the master",
        ).set_function(lambda: self.num_running_workers)
        self._step_time_hist = registry.histogram(
            "dlrover_tpu_train_step_time_seconds",
            "Wall-clock per training step, from step-report deltas",
        )

    # -- sample collection -------------------------------------------------
    def collect_global_step(self, step: int,
                            timestamp: Optional[float] = None) -> None:
        timestamp = timestamp or time.time()
        step_time: Optional[float] = None
        with self._lock:
            if step <= self._global_step:
                return
            if self._first_step_time is None:
                self._first_step_time = timestamp
            elif self._skip_next_step_time:
                # this delta spans the failover gap, not training
                self._skip_next_step_time = False
            elif timestamp > self._last_step_time:
                # mean per-step wall time since the previous report
                step_time = ((timestamp - self._last_step_time)
                             / (step - self._global_step))
            self._global_step = step
            self._last_step_time = timestamp
            self._samples.append((timestamp, step))
        if step_time is not None:
            self._step_time_hist.observe(step_time)

    def collect_worker_step(self, worker_id: int, step: int) -> None:
        with self._lock:
            self._worker_steps[worker_id] = step
        self.collect_global_step(step)

    def set_start_training(self) -> None:
        with self._lock:
            if self._start_training_time is None:
                self._start_training_time = time.time()

    def set_tokens_per_step(self, tokens: int) -> None:
        """From ModelInfo (batch_size × seq_len): scales steps/s into the
        tokens/s exposition series."""
        with self._lock:
            if tokens > 0:
                self._tokens_per_step = int(tokens)

    # -- queries -----------------------------------------------------------
    @property
    def completed_global_step(self) -> int:
        with self._lock:
            return self._global_step

    @property
    def num_running_workers(self) -> int:
        with self._lock:
            return len(self._workers)

    def running_speed(self) -> float:
        """Steps/second over the sample window."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            (t0, s0), (t1, s1) = self._samples[0], self._samples[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    def tokens_per_second(self) -> float:
        with self._lock:
            tokens = self._tokens_per_step
        return self.running_speed() * tokens

    def all_worker_joined(self, expected: int) -> bool:
        with self._lock:
            return len(self._workers) >= expected

    def add_running_worker(self, worker_id: int) -> None:
        with self._lock:
            self._workers.add(worker_id)

    def remove_running_worker(self, worker_id: int) -> None:
        with self._lock:
            self._workers.discard(worker_id)
            self._worker_steps.pop(worker_id, None)

    def is_hanged(self, hang_seconds: Optional[float] = None) -> bool:
        """No step progress for hang_seconds while training had started."""
        hang_seconds = hang_seconds or Context.singleton().hang_seconds
        with self._lock:
            if self._first_step_time is None:
                return False
            return (time.time() - self._last_step_time) > hang_seconds

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        with self._lock:
            return {"global_step": self._global_step,
                    "tokens_per_step": self._tokens_per_step}

    def restore_state(self, state: dict) -> None:
        """Rehydrate the step high-water mark so post-failover hang
        detection and the exposition don't restart from 0. Wall-clock
        fields restart fresh: the first step delta after a master restart
        spans the outage, not training."""
        with self._lock:
            self._global_step = int(state.get("global_step", 0))
            self._tokens_per_step = int(state.get("tokens_per_step", 0))
            self._last_step_time = time.time()
            self._samples.clear()
            self._skip_next_step_time = True

    def reset_running_speed(self) -> None:
        """Call at membership change: old samples reflect the old world,
        and the next step-report delta spans the failover gap — neither
        belongs in the steady-state series."""
        with self._lock:
            self._samples.clear()
            self._skip_next_step_time = True
