"""Global-step speed monitoring and hang detection.

Capability parity: dlrover/python/master/monitor/speed_monitor.py:43 —
collect (timestamp, global_step) samples, compute windowed throughput,
track per-worker step reports, and flag a hang when no step progress is made
for `hang_seconds`.

Publishes through the obs metrics registry (docs/observability.md):
``dlrover_tpu_training_global_step`` / ``_steps_per_second`` /
``_tokens_per_second`` collect-time gauges and the
``dlrover_tpu_train_step_time_seconds`` histogram observed per step
report. All shared step/worker state is written from servicer threads
and read from the master watch loop + metrics scrapes — every access
goes through ``self._lock``; registry observes happen OUTSIDE the lock
(sinks must never run under it).
"""

from __future__ import annotations

import dataclasses
import statistics
import threading
import time
from collections import deque
from typing import Deque, Dict, Iterable, Optional, Set, Tuple

from dlrover_tpu import obs
from dlrover_tpu.common.config import Context


@dataclasses.dataclass
class WorkerSpeed:
    """Windowed per-worker speed evidence (the diagnosis engine's straggler
    input): means over the last `samples` step reports that carried
    timing (worker timelines, obs/timeline.py)."""

    worker_id: int
    samples: int = 0
    mean_step_time_s: float = 0.0
    data_wait_fraction: float = -1.0   # -1 = no timeline evidence
    last_report_ts: float = 0.0
    step: int = 0
    mfu: float = -1.0                  # -1 = no FLOPs model evidence


class SpeedMonitor:
    def __init__(self):
        self._lock = threading.Lock()
        ctx = Context.singleton()
        self._samples: Deque[Tuple[float, int]] = deque(
            maxlen=ctx.speed_sample_window
        )
        self._global_step = 0
        # graftlint: ephemeral(this incarnation's clock anchor)
        self._first_step_time: Optional[float] = None
        self._last_step_time: float = time.time()
        # graftlint: ephemeral(re-learned from the next step reports)
        self._workers: Set[int] = set()
        # graftlint: ephemeral(re-learned from the next step reports)
        self._worker_steps: Dict[int, int] = {}
        # worker_id -> deque[(step_time_s, data_wait_fraction, mfu, ts)]
        # from step reports that carried timing evidence
        self._worker_window = max(2, ctx.diagnosis_worker_window)
        self._worker_times: Dict[
            int, Deque[Tuple[float, float, float, float]]] = {}
        # worker_id -> deque[(latency_s, records, ts)] from completed
        # data-shard tasks (TaskManager.report_dataset_task): the only
        # per-rank speed evidence during data-only warmup, before any
        # step report carries timing — dispatch weighting must not fly
        # blind there.
        # graftlint: ephemeral(re-learned from the next task completions)
        self._task_latency: Dict[int, Deque[Tuple[float, int, float]]] = {}
        # steps/s high-water mark over the job (throughput-collapse
        # baseline; survives window resets, cleared on restore)
        self._peak_speed = 0.0
        # graftlint: ephemeral(wall-clock anchor of THIS incarnation)
        self._start_training_time: Optional[float] = None
        self._paused_time_s: float = 0.0
        self._tokens_per_step: int = 0
        self._seq_len: int = 0
        # model-FLOPs accounting (obs/mfu.py, fed by ModelInfo): the
        # job's MFU exposition is tokens/s × flops_per_token / peak.
        # The per-chip peak is kept separately so a parallelism re-plan
        # can re-anchor the aggregate to the NEW chip count instead of
        # reporting post-resize MFU against the old denominator.
        self._flops_per_token: float = 0.0
        self._peak_flops_total: float = 0.0
        self._peak_flops_per_chip: float = 0.0
        # set at membership change: the NEXT step-report delta spans the
        # failover gap (rendezvous + recompile + restore), not step time
        self._skip_next_step_time = False
        # multi-slice hierarchical DP: rank → slice (from the rendezvous
        # slice registry) + the slice label-pairs currently published,
        # so a departing slice's series evict as a unit
        # graftlint: ephemeral(re-pushed at JobMaster._restore_state)
        self._slice_map: Dict[int, int] = {}
        # graftlint: ephemeral(gauge dedup; republished next tick)
        self._published_slices: Set[str] = set()
        self._publish_metrics()

    def _publish_metrics(self) -> None:
        """Collect-time gauges: scrapes read live values through the
        monitor's own locked queries (the newest monitor instance in a
        process wins the registration — matching the newest master)."""
        registry = obs.get_registry()
        registry.gauge(
            "dlrover_tpu_training_global_step",
            "Latest global step reported by any worker",
        ).set_function(lambda: self.completed_global_step)
        registry.gauge(
            "dlrover_tpu_training_steps_per_second",
            "Windowed training throughput",
        ).set_function(self.running_speed)
        registry.gauge(
            "dlrover_tpu_training_tokens_per_second",
            "Windowed throughput x tokens per step (from ModelInfo)",
        ).set_function(self.tokens_per_second)
        registry.gauge(
            "dlrover_tpu_training_running_workers",
            "Workers currently joined on the master",
        ).set_function(lambda: self.num_running_workers)
        registry.gauge(
            "dlrover_tpu_training_mfu",
            "Job model-FLOPs utilization: tokens/s x FLOPs-per-token "
            "over the world's aggregate peak (-1 = no FLOPs model yet)",
        ).set_function(self.running_mfu)
        registry.gauge(
            "dlrover_tpu_training_model_flops_per_token",
            "Model FLOPs per trained token (ModelInfo; obs/mfu.py)",
        ).set_function(lambda: self._model_flops())
        self._step_time_hist = registry.histogram(
            "dlrover_tpu_train_step_time_seconds",
            "Wall-clock per training step, from step-report deltas",
        )
        # per-slice aggregates (multi-slice hierarchical DP): published
        # explicitly on step reports — label sets are dynamic
        self._slice_steps_gauge = registry.gauge(
            "dlrover_tpu_slice_steps_per_second",
            "Windowed steps/s of one slice's workers (1 / mean step "
            "time over the slice's report windows)",
            labelnames=("slice",))
        self._slice_mfu_gauge = registry.gauge(
            "dlrover_tpu_slice_mfu",
            "Windowed mean achieved MFU of one slice's workers",
            labelnames=("slice",))
        self._slice_workers_gauge = registry.gauge(
            "dlrover_tpu_slice_workers",
            "Workers of one slice currently reporting speed evidence",
            labelnames=("slice",))

    # -- sample collection -------------------------------------------------
    def collect_global_step(self, step: int,
                            timestamp: Optional[float] = None) -> None:
        timestamp = timestamp or time.time()
        step_time: Optional[float] = None
        with self._lock:
            if step <= self._global_step:
                return
            if self._first_step_time is None:
                self._first_step_time = timestamp
            elif self._skip_next_step_time:
                # this delta spans the failover gap, not training
                self._skip_next_step_time = False
            elif timestamp > self._last_step_time:
                # mean per-step wall time since the previous report
                step_time = ((timestamp - self._last_step_time)
                             / (step - self._global_step))
            self._global_step = step
            self._last_step_time = timestamp
            self._samples.append((timestamp, step))
            speed = self._window_speed_locked()
            if speed > self._peak_speed:
                self._peak_speed = speed
        if step_time is not None:
            self._step_time_hist.observe(step_time)

    def collect_worker_step(self, worker_id: int, step: int,
                            step_time_s: float = 0.0,
                            data_wait_fraction: float = -1.0,
                            mfu: float = -1.0,
                            timestamp: Optional[float] = None) -> None:
        timestamp = timestamp or time.time()
        with self._lock:
            self._worker_steps[worker_id] = step
            if step_time_s > 0.0:
                window = self._worker_times.get(worker_id)
                if window is None:
                    window = deque(maxlen=self._worker_window)
                    self._worker_times[worker_id] = window
                window.append((step_time_s, data_wait_fraction, mfu,
                               timestamp))
            slice_view = (self._slice_rollup_locked()
                          if self._slice_map else None)
        if slice_view is not None:
            self._publish_slice_gauges(slice_view)
        self.collect_global_step(step, timestamp)

    def collect_task_latency(self, worker_id: int, latency_s: float,
                             records: int,
                             timestamp: Optional[float] = None) -> None:
        """Per-rank data-shard completion latency, fed by
        TaskManager.report_dataset_task on every successful shard.
        Unlike step timing (gated on step_time_s > 0) this exists from
        the very first completed shard, so speed-weighted dispatch has
        evidence during the data-only warmup when no step report has
        carried timing yet."""
        if latency_s <= 0.0 or records <= 0:
            return
        timestamp = timestamp or time.time()
        with self._lock:
            window = self._task_latency.get(worker_id)
            if window is None:
                window = deque(maxlen=self._worker_window)
                self._task_latency[worker_id] = window
            window.append((latency_s, records, timestamp))

    def relative_speeds(self) -> Dict[int, float]:
        """Per-rank speed score: 1.0 = at the pack's pace, <1 slower,
        >1 faster. Ranks with step-timing evidence are scored against
        the fleet's median step time; ranks with ONLY task-latency
        evidence (data-only warmup) against the median records/s of
        that class. The two classes never share a denominator — a shard
        fetch and a training step are not the same kind of second."""
        with self._lock:
            step_mean: Dict[int, float] = {}
            for worker_id, window in self._worker_times.items():
                times = [t for t, _, _, _ in window]
                if times:
                    step_mean[worker_id] = sum(times) / len(times)
            task_rate: Dict[int, float] = {}
            for worker_id, window in self._task_latency.items():
                if worker_id in step_mean or not window:
                    continue
                lat = sum(entry[0] for entry in window)
                recs = sum(entry[1] for entry in window)
                if lat > 0.0 and recs > 0:
                    task_rate[worker_id] = recs / lat
        out: Dict[int, float] = {}
        if step_mean:
            med = statistics.median(step_mean.values())
            if med > 0.0:
                out.update({w: med / t for w, t in step_mean.items()
                            if t > 0.0})
        if task_rate:
            med = statistics.median(task_rate.values())
            if med > 0.0:
                out.update({w: r / med for w, r in task_rate.items()})
        return out

    # -- per-slice aggregates (multi-slice hierarchical DP) ----------------
    def set_slice_map(self, slice_map: Dict[int, int]) -> None:
        with self._lock:
            self._slice_map = dict(slice_map)

    def _slice_rollup_locked(self) -> Dict[str, Tuple[float, float, int]]:
        """(lock held) slice label → (steps/s, mean mfu, workers) from
        the per-worker timing windows."""
        per_slice: Dict[str, list] = {}
        for worker_id, window in self._worker_times.items():
            if not window:
                continue
            label = str(self._slice_map.get(worker_id, -1))
            per_slice.setdefault(label, []).append(window)
        rollup: Dict[str, Tuple[float, float, int]] = {}
        for label, windows in per_slice.items():
            times = [t for w in windows for t, _, _, _ in w]
            mfus = [m for w in windows for _, _, m, _ in w if m >= 0.0]
            mean_t = sum(times) / len(times) if times else 0.0
            rollup[label] = (
                1.0 / mean_t if mean_t > 0 else 0.0,
                sum(mfus) / len(mfus) if mfus else -1.0,
                len(windows),
            )
        return rollup

    def _publish_slice_gauges(
            self, rollup: Dict[str, Tuple[float, float, int]]) -> None:
        """Registry ops OUTSIDE the monitor lock. A slice with no
        reporting workers left (whole-slice departure) has its series
        removed as a unit."""
        for label, (steps_s, mfu, workers) in rollup.items():
            self._slice_steps_gauge.labels(slice=label).set(steps_s)
            self._slice_workers_gauge.labels(slice=label).set(workers)
            if mfu >= 0.0:
                self._slice_mfu_gauge.labels(slice=label).set(mfu)
            else:
                # the slice no longer reports an MFU (workers restarted
                # without a FLOPs model): a stale last value must not
                # keep scraping as current
                self._slice_mfu_gauge.remove(slice=label)
        with self._lock:
            stale = self._published_slices - set(rollup)
            self._published_slices = set(rollup)
        for label in stale:
            self._slice_steps_gauge.remove(slice=label)
            self._slice_workers_gauge.remove(slice=label)
            self._slice_mfu_gauge.remove(slice=label)

    def set_start_training(self) -> None:
        with self._lock:
            if self._start_training_time is None:
                self._start_training_time = time.time()

    def set_tokens_per_step(self, tokens: int, seq_len: int = 0) -> None:
        """From ModelInfo (batch_size × seq_len): scales steps/s into the
        tokens/s exposition series."""
        with self._lock:
            if tokens > 0:
                self._tokens_per_step = int(tokens)
            if seq_len > 0:
                self._seq_len = int(seq_len)

    @property
    def seq_len_hint(self) -> int:
        """Last reported sequence length (0 = never reported): lets a
        re-plan derive the new tokens-per-step from its planned batch
        before any worker of the new world has reported."""
        with self._lock:
            return self._seq_len

    def set_model_flops(self, flops_per_token: float,
                        peak_flops_total: float,
                        peak_flops_per_chip: float = 0.0) -> None:
        """From ModelInfo: the FLOPs model + aggregate peak that turn the
        tokens/s series into the MFU gauge."""
        with self._lock:
            if flops_per_token > 0.0:
                self._flops_per_token = float(flops_per_token)
            if peak_flops_total > 0.0:
                self._peak_flops_total = float(peak_flops_total)
            if peak_flops_per_chip > 0.0:
                self._peak_flops_per_chip = float(peak_flops_per_chip)

    def reanchor_plan(self, chips: int = 0,
                      tokens_per_step: int = 0) -> None:
        """A parallelism re-plan changed the world's execution shape:
        recompute every denominator derived from it. The aggregate
        peak re-anchors to the NEW chip count (from the stored
        per-chip peak) and tokens/s to the planned (possibly
        deliberately adjusted) batch — post-resize MFU must never be
        reported against the old world's denominators. Windowed
        samples and the peak-speed baseline reset like any membership
        change (they describe the OLD shape's throughput)."""
        with self._lock:
            if tokens_per_step > 0:
                self._tokens_per_step = int(tokens_per_step)
            if chips > 0 and self._peak_flops_per_chip > 0.0:
                self._peak_flops_total = (self._peak_flops_per_chip
                                          * chips)
            self._samples.clear()
            self._skip_next_step_time = True
            self._peak_speed = 0.0
            self._worker_times.clear()
            self._task_latency.clear()

    def _model_flops(self) -> float:
        with self._lock:
            return self._flops_per_token

    # -- queries -----------------------------------------------------------
    @property
    def completed_global_step(self) -> int:
        with self._lock:
            return self._global_step

    @property
    def num_running_workers(self) -> int:
        with self._lock:
            return len(self._workers)

    def running_speed(self) -> float:
        """Steps/second over the sample window."""
        with self._lock:
            return self._window_speed_locked()

    def _window_speed_locked(self) -> float:
        if len(self._samples) < 2:
            return 0.0
        (t0, s0), (t1, s1) = self._samples[0], self._samples[-1]
        if t1 <= t0:
            return 0.0
        return (s1 - s0) / (t1 - t0)

    def peak_speed(self) -> float:
        """Steps/s high-water mark of the CURRENT world (reset at
        membership change — a smaller world's sustainable speed is a new
        baseline, not a collapse)."""
        with self._lock:
            return self._peak_speed

    def running_mfu(self) -> float:
        """Job MFU from the windowed throughput; -1 with no FLOPs
        model (callers must not mistake "no evidence" for 0%)."""
        from dlrover_tpu.obs import mfu as mfu_math

        with self._lock:
            tokens = self._tokens_per_step
            fpt = self._flops_per_token
            peak = self._peak_flops_total
        return mfu_math.achieved_mfu(self.running_speed() * tokens,
                                     fpt, peak)

    def peak_mfu(self) -> float:
        """MFU at this world's steps/s high-water mark (the collapse
        rule's MFU baseline); -1 with no FLOPs model."""
        from dlrover_tpu.obs import mfu as mfu_math

        with self._lock:
            tokens = self._tokens_per_step
            fpt = self._flops_per_token
            peak = self._peak_flops_total
            peak_speed = self._peak_speed
        return mfu_math.achieved_mfu(peak_speed * tokens, fpt, peak)

    def worker_speeds(self) -> Dict[int, WorkerSpeed]:
        """Windowed per-worker means for the diagnosis engine (only
        workers whose reports carried timing evidence appear)."""
        with self._lock:
            out: Dict[int, WorkerSpeed] = {}
            for worker_id, window in self._worker_times.items():
                if not window:
                    continue
                times = [t for t, _, _, _ in window]
                waits = [w for _, w, _, _ in window if w >= 0.0]
                mfus = [m for _, _, m, _ in window if m >= 0.0]
                out[worker_id] = WorkerSpeed(
                    worker_id=worker_id,
                    samples=len(window),
                    mean_step_time_s=sum(times) / len(times),
                    data_wait_fraction=(sum(waits) / len(waits)
                                        if waits else -1.0),
                    last_report_ts=window[-1][3],
                    step=self._worker_steps.get(worker_id, 0),
                    mfu=(sum(mfus) / len(mfus) if mfus else -1.0),
                )
            return out

    def evict_departed(self, live: Iterable[int]) -> Set[int]:
        """Drop per-worker state for every worker NOT in ``live`` (the
        membership-change hook): straggler scoring and per-worker gauges
        must never rank dead ranks. Returns the evicted ids."""
        live_set = set(live)
        with self._lock:
            departed = ((set(self._worker_steps)
                         | set(self._worker_times)
                         | set(self._task_latency)
                         | self._workers) - live_set)
            for worker_id in departed:
                self._workers.discard(worker_id)
                self._worker_steps.pop(worker_id, None)
                self._worker_times.pop(worker_id, None)
                self._task_latency.pop(worker_id, None)
            slice_view = (self._slice_rollup_locked()
                          if self._slice_map else None)
        if slice_view is not None and departed:
            # whole-slice eviction: a slice whose last member departed
            # drops out of the rollup, so its labeled series remove here
            self._publish_slice_gauges(slice_view)
        return departed

    def tokens_per_second(self) -> float:
        with self._lock:
            tokens = self._tokens_per_step
        return self.running_speed() * tokens

    def all_worker_joined(self, expected: int) -> bool:
        with self._lock:
            return len(self._workers) >= expected

    def add_running_worker(self, worker_id: int) -> None:
        with self._lock:
            self._workers.add(worker_id)

    def remove_running_worker(self, worker_id: int) -> None:
        with self._lock:
            self._workers.discard(worker_id)
            self._worker_steps.pop(worker_id, None)
            self._worker_times.pop(worker_id, None)
            self._task_latency.pop(worker_id, None)

    def is_hanged(self, hang_seconds: Optional[float] = None) -> bool:
        """No step progress for hang_seconds while training had started."""
        hang_seconds = hang_seconds or Context.singleton().hang_seconds
        with self._lock:
            if self._first_step_time is None:
                return False
            return (time.time() - self._last_step_time) > hang_seconds

    # -- crash-consistent state (master/state_backend.py) ------------------
    def export_state(self) -> dict:
        with self._lock:
            return {"global_step": self._global_step,
                    "tokens_per_step": self._tokens_per_step,
                    "seq_len": self._seq_len,
                    "flops_per_token": self._flops_per_token,
                    "peak_flops_total": self._peak_flops_total,
                    "peak_flops_per_chip": self._peak_flops_per_chip}

    def restore_state(self, state: dict) -> None:
        """Rehydrate the step high-water mark so post-failover hang
        detection and the exposition don't restart from 0. Wall-clock
        fields restart fresh: the first step delta after a master restart
        spans the outage, not training."""
        with self._lock:
            self._global_step = int(state.get("global_step", 0))
            self._tokens_per_step = int(state.get("tokens_per_step", 0))
            self._seq_len = int(state.get("seq_len", 0))
            self._flops_per_token = float(
                state.get("flops_per_token", 0.0))
            self._peak_flops_total = float(
                state.get("peak_flops_total", 0.0))
            self._peak_flops_per_chip = float(
                state.get("peak_flops_per_chip", 0.0))
            self._last_step_time = time.time()
            self._samples.clear()
            self._skip_next_step_time = True
            self._peak_speed = 0.0
            self._worker_times.clear()
            self._task_latency.clear()

    def reset_running_speed(self) -> None:
        """Call at membership change: old samples reflect the old world,
        and the next step-report delta spans the failover gap — neither
        belongs in the steady-state series. The peak-speed baseline and
        per-worker timing windows reset too: they describe the OLD
        world's sustainable throughput."""
        with self._lock:
            self._samples.clear()
            self._skip_next_step_time = True
            self._peak_speed = 0.0
            self._worker_times.clear()
            self._task_latency.clear()
