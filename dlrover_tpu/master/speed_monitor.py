"""Global-step speed monitoring and hang detection.

Capability parity: dlrover/python/master/monitor/speed_monitor.py:43 —
collect (timestamp, global_step) samples, compute windowed throughput,
track per-worker step reports, and flag a hang when no step progress is made
for `hang_seconds`.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Set, Tuple

from dlrover_tpu.common.config import Context


class SpeedMonitor:
    def __init__(self):
        self._lock = threading.Lock()
        ctx = Context.singleton()
        self._samples: Deque[Tuple[float, int]] = deque(
            maxlen=ctx.speed_sample_window
        )
        self._global_step = 0
        self._first_step_time: Optional[float] = None
        self._last_step_time: float = time.time()
        self._workers: Set[int] = set()
        self._worker_steps: Dict[int, int] = {}
        self._start_training_time: Optional[float] = None
        self._paused_time_s: float = 0.0

    # -- sample collection -------------------------------------------------
    def collect_global_step(self, step: int,
                            timestamp: Optional[float] = None) -> None:
        timestamp = timestamp or time.time()
        with self._lock:
            if step <= self._global_step:
                return
            if self._first_step_time is None:
                self._first_step_time = timestamp
            self._global_step = step
            self._last_step_time = timestamp
            self._samples.append((timestamp, step))

    def collect_worker_step(self, worker_id: int, step: int) -> None:
        with self._lock:
            self._worker_steps[worker_id] = step
        self.collect_global_step(step)

    def set_start_training(self) -> None:
        with self._lock:
            if self._start_training_time is None:
                self._start_training_time = time.time()

    # -- queries -----------------------------------------------------------
    @property
    def completed_global_step(self) -> int:
        with self._lock:
            return self._global_step

    def running_speed(self) -> float:
        """Steps/second over the sample window."""
        with self._lock:
            if len(self._samples) < 2:
                return 0.0
            (t0, s0), (t1, s1) = self._samples[0], self._samples[-1]
            if t1 <= t0:
                return 0.0
            return (s1 - s0) / (t1 - t0)

    def all_worker_joined(self, expected: int) -> bool:
        with self._lock:
            return len(self._workers) >= expected

    def add_running_worker(self, worker_id: int) -> None:
        with self._lock:
            self._workers.add(worker_id)

    def remove_running_worker(self, worker_id: int) -> None:
        with self._lock:
            self._workers.discard(worker_id)
            self._worker_steps.pop(worker_id, None)

    def is_hanged(self, hang_seconds: Optional[float] = None) -> bool:
        """No step progress for hang_seconds while training had started."""
        hang_seconds = hang_seconds or Context.singleton().hang_seconds
        with self._lock:
            if self._first_step_time is None:
                return False
            return (time.time() - self._last_step_time) > hang_seconds

    def reset_running_speed(self) -> None:
        """Call at membership change: old samples reflect the old world."""
        with self._lock:
            self._samples.clear()
