"""The coordination tier: KV + slice-status RPCs on their own port.

Since PR 8 every per-step cross-slice gradient exchange rides the master
KV store (``dcn/`` keys, parallel/dcn_sync.py) — through the SAME gRPC
server, thread pool and dispatch path as rendezvous joins, telemetry
batches and diagnosis polls. A join storm (1k agents re-forming) or a
telemetry flood could therefore stall a training step's ``dcn/`` read,
and vice versa. This module splits the coordination tier out:

- :class:`CoordServicer` answers exactly the gradient-path RPCs —
  ``KVGetRequest`` / ``KVWaitRequest`` / ``KeyValuePair`` /
  ``KVAddRequest`` / ``SliceStatusRequest`` — against the SAME
  ``KVStoreService`` and rendezvous registry the main servicer uses, on
  its OWN server + port with its own (small) thread pool. Reads are
  lock-free (kv_store.get), so the tier's latency is bounded by the wire,
  not by whatever the control tier is doing.
- :class:`TelemetryIngestQueue` bounds the OTHER direction: telemetry
  reports are enqueued (drop-oldest past ``telemetry_queue_size``,
  counted in ``dlrover_tpu_telemetry_dropped_total``) and replayed onto
  the registry by one background thread — a span storm degrades
  observability, never liveness.

The main servicer keeps answering every coordination RPC too (agents
that predate the split — or jobs with ``coord_port`` -1 — never dial the
second port). The coordination address rides the bootstrap file and the
join/reconnect results; MasterClient routes HOT-prefix KV traffic there
(agent/master_client.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Callable, Optional

import grpc

from dlrover_tpu import obs
from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.master.kv_store import KVStoreService


class CoordServicer:
    """Dispatch for the coordination tier. Thin by design: every
    decision lives in the shared components; a request outside the
    coordination surface is answered with a clean failure (the client
    falls back to the main tier)."""

    def __init__(self, kv_store: KVStoreService,
                 rdzv_manager=None, speed_monitor=None,
                 state_sink: Optional[Callable] = None):
        self.kv_store = kv_store
        self.rdzv_manager = rdzv_manager
        self.speed_monitor = speed_monitor
        # cold keys arriving here still get crash-consistency (an old
        # client routing everything through one addr must lose nothing);
        # hot keys deliberately bypass it — that is the tier's point
        self.state_sink = state_sink

    # -- raw byte endpoints (wired into comm.build_server) ---------------
    def get_bytes(self, payload: bytes,
                  context: Optional[grpc.ServicerContext] = None
                  ) -> bytes:
        try:
            request = msg.deserialize_message(payload)
            response = self.get(request)
        except Exception:
            logger.exception("coord get failed (payload %d bytes)",
                             len(payload))
            response = msg.Response(success=False, reason="internal error")
        return msg.serialize_message(response)

    def report_bytes(self, payload: bytes,
                     context: Optional[grpc.ServicerContext] = None
                     ) -> bytes:
        try:
            request = msg.deserialize_message(payload)
            response = self.report(request)
        except Exception:
            logger.exception("coord report failed (payload %d bytes)",
                             len(payload))
            response = msg.Response(success=False, reason="internal error")
        return msg.serialize_message(response)

    # -- typed dispatch ---------------------------------------------------
    def get(self, request: msg.Message) -> msg.Message:
        if isinstance(request, msg.KVGetRequest):
            return msg.KeyValuePair(key=request.key,
                                    value=self.kv_store.get(request.key))
        if isinstance(request, msg.KVWaitRequest):
            # a SHORTER window than the main tier's 20 s: blocked waits
            # hold tier threads, and this tier's whole point is that a
            # wait pile-up (world formation) can never starve another
            # slice's per-step dcn/ gets. The client's kv_wait loop
            # re-issues until its own deadline either way.
            ok = self.kv_store.wait(request.keys,
                                    min(request.timeout_s, 5.0))
            return msg.Response(success=ok)
        if isinstance(request, msg.SliceStatusRequest):
            import json

            if self.rdzv_manager is None:
                return msg.SliceStatus(status_json="")
            status = self.rdzv_manager.slice_status()
            if self.speed_monitor is not None:
                status["fleet_step"] = (
                    self.speed_monitor.completed_global_step)
            return msg.SliceStatus(status_json=json.dumps(status))
        return msg.Response(
            success=False,
            reason=f"{type(request).__name__} is not a coordination-"
                   f"tier request")

    def report(self, request: msg.Message) -> msg.Message:
        if isinstance(request, msg.KeyValuePair):
            self.kv_store.set(request.key, request.value)
            self._sink_if_cold(request.key)
            return msg.Response(success=True)
        if isinstance(request, msg.KVAddRequest):
            value = self.kv_store.add(request.key, request.amount)
            self._sink_if_cold(request.key)
            return msg.KVIntResult(value=value)
        return msg.Response(
            success=False,
            reason=f"{type(request).__name__} is not a coordination-"
                   f"tier request")

    def _sink_if_cold(self, key: str) -> None:
        """Hot keys ride the mutation log; a cold key landing here still
        deserves a snapshot. Failures never fail the RPC."""
        if self.state_sink is None or self.kv_store.is_hot(key):
            return
        try:
            self.state_sink()
        except Exception:  # noqa: BLE001 — durability is best-effort
            logger.exception("coord-tier state snapshot failed")


class TelemetryIngestQueue:
    """Bounded drop-oldest ingest between the telemetry RPC and the
    registry replay. The RPC handler only appends; one daemon thread
    drains. Full queue → the OLDEST report is dropped and counted — a
    span storm can cost observability samples, never master liveness."""

    def __init__(self, process_fn: Callable, maxlen: int = 256):
        self._process = process_fn
        self._maxlen = max(1, maxlen)
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        self._stopped = False
        self._processed = 0
        # the report the drainer popped but has not finished replaying:
        # flush() must wait it out too, or a caller could observe an
        # empty queue with the last report still mid-replay
        self._in_flight = 0
        self.dropped_total = 0
        self._dropped_counter = obs.get_registry().counter(
            "dlrover_tpu_telemetry_dropped_total",
            "Telemetry reports dropped (oldest-first) because the "
            "bounded ingest queue was full")

    def push(self, report) -> None:
        with self._cond:
            if len(self._queue) >= self._maxlen:
                self._queue.popleft()
                self.dropped_total += 1
                dropped = True
            else:
                dropped = False
            self._queue.append(report)
            if self._thread is None and not self._stopped:
                self._thread = threading.Thread(
                    target=self._drain, daemon=True,
                    name="telemetry-ingest")
                self._thread.start()
            self._cond.notify_all()
        if dropped:
            # registry ops outside the queue lock (they take their own)
            self._dropped_counter.inc()

    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stopped:
                    self._cond.wait()
                if self._stopped and not self._queue:
                    return
                report = self._queue.popleft()
                self._in_flight += 1
            try:
                self._process(report)
            except Exception:  # noqa: BLE001 — one bad report must not
                # kill the drainer (and with it all future telemetry)
                logger.exception("telemetry report processing failed")
            with self._cond:
                self._in_flight -= 1
                self._processed += 1
                self._cond.notify_all()

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until everything pushed so far is processed (tests +
        graceful master stop). Returns False on timeout."""
        import time

        deadline = time.time() + timeout_s
        with self._cond:
            while self._queue or self._in_flight:
                remaining = deadline - time.time()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def stop(self) -> None:
        with self._cond:
            self._stopped = True
            thread = self._thread
            self._cond.notify_all()
        if thread is not None:
            thread.join(timeout=2.0)
