"""Master-side node lifecycle management (reference: dlrover/python/master/node/)."""

from dlrover_tpu.master.node.job_manager import JobManager, create_job_manager

__all__ = ["JobManager", "create_job_manager"]
