"""Job manager: node lifecycle, relaunch decisions, job stage.

Capability parity: DistributedJobManager (dlrover/python/master/node/
dist_job_manager.py:87-737) — initializes the node set from JobArgs, issues
the initial ScalePlan, consumes watcher events through the node state
machine (common/node.py NODE_STATE_FLOWS), decides relaunches by exit
reason (:400-544: FATAL never; OOM with more memory; budget-capped
otherwise), fails the job when a critical node is unrecoverable, and
detects hang from heartbeats + the speed monitor.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from dlrover_tpu.common import messages as msg
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import (
    JobStage,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
    PlatformType,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import (
    Node,
    NodeGroupResource,
    get_node_state_flow,
)
from dlrover_tpu.master.node.event_callback import NodeEventCallback
from dlrover_tpu.master.scaler.base import ScalePlan, Scaler
from dlrover_tpu.master.watcher.base import NodeEvent, NodeWatcher
from dlrover_tpu.scheduler.job import JobArgs

# Memory bump applied when relaunching an OOM-killed node (the local analog
# of the brain's optimize_job_worker_create_oom_resource algorithm).
_OOM_MEMORY_FACTOR = 1.5


class JobManager:
    def __init__(
        self,
        job_args: JobArgs,
        scaler: Scaler,
        watcher: NodeWatcher,
        speed_monitor=None,
    ):
        self._job_args = job_args
        self._scaler = scaler
        self._watcher = watcher
        self._speed_monitor = speed_monitor
        self._nodes: Dict[str, Dict[int, Node]] = {}
        self._lock = threading.Lock()
        self._stopped = threading.Event()
        self._stage = JobStage.CREATED
        self._exit_reason = ""
        # graftlint: ephemeral(wiring; JobMaster re-registers callbacks at start)
        self._event_callbacks: List[NodeEventCallback] = []
        # graftlint: ephemeral(thread handles; start() spawns fresh ones)
        self._threads: List[threading.Thread] = []
        self._relaunch_always = job_args.relaunch_always
        self._model_info: Optional[msg.ModelInfo] = None
        self._paral_config: Optional[msg.ParallelConfig] = None

    # -- setup ---------------------------------------------------------
    def add_event_callback(self, callback: NodeEventCallback) -> None:
        self._event_callbacks.append(callback)

    def _init_nodes(self) -> None:
        """Materialize the Node table from JobArgs (reference:
        _init_nodes, dist_job_manager.py:262-292). Node groups already
        populated by a state-backend restore keep their restored table —
        re-materializing would zero every relaunch budget."""
        with self._lock:
            for node_type, args in self._job_args.node_args.items():
                if self._nodes.get(node_type):
                    continue
                group = args.group_resource
                self._nodes[node_type] = {}
                for node_id in range(group.count):
                    node = Node(
                        node_type,
                        node_id,
                        rank_index=node_id,
                        config_resource=group.node_resource,
                        critical=args.critical,
                        max_relaunch_count=args.restart_count,
                    )
                    node.create_time = time.time()
                    self._nodes[node_type][node_id] = node

    def _initial_scale_plan(self) -> ScalePlan:
        plan = ScalePlan()
        for node_type, args in self._job_args.node_args.items():
            plan.node_group_resources[node_type] = NodeGroupResource(
                count=args.group_resource.count,
                node_resource=args.group_resource.node_resource,
            )
        return plan

    def start(self) -> None:
        # _stage is read by servicer threads and written by the monitor
        # thread: every access holds the lock
        with self._lock:
            self._stage = JobStage.RUNNING
        self._init_nodes()
        self._watcher.prime()
        self._scaler.start()
        self._scaler.scale(self._initial_scale_plan())
        monitor = threading.Thread(target=self._monitor_nodes, daemon=True,
                                   name="node-monitor")
        monitor.start()
        self._threads.append(monitor)

    def stop(self) -> None:
        self._stopped.set()
        self._watcher.stop()
        self._scaler.stop()

    # -- monitoring ----------------------------------------------------
    def _monitor_nodes(self) -> None:
        while not self._stopped.is_set():
            try:
                for event in self._watcher.watch():
                    if self._stopped.is_set():
                        return
                    self._process_event(event)
            except Exception as e:  # noqa: BLE001 - monitor must survive
                logger.warning("node monitor error: %s; relisting", e)
                for node in self._watcher.list():
                    self._process_event(
                        NodeEvent(NodeEventType.MODIFIED, node))
                time.sleep(1.0)

    def _process_event(self, event: NodeEvent) -> None:
        reported = event.node
        with self._lock:
            by_id = self._nodes.setdefault(reported.type, {})
            node = by_id.get(reported.id)
            if node is None:
                # a node we didn't launch (e.g. after master restart):
                # adopt it
                node = reported
                by_id[reported.id] = node
        flow = get_node_state_flow(node.status, event.event_type,
                                   reported.status)
        if flow is None:
            return
        node.exit_reason = reported.exit_reason or node.exit_reason
        if reported.host_addr:
            node.host_addr = reported.host_addr
        node.update_status(flow.to_status)
        logger.info("node %s: %s -> %s (%s)", node.name, flow.from_status,
                    flow.to_status, node.exit_reason or "-")
        self._fire_callbacks(node, flow.to_status)
        if flow.should_relaunch and self._should_relaunch(node):
            self._relaunch_node(node)
        self._update_job_stage()

    def _fire_callbacks(self, node: Node, status: str) -> None:
        for cb in self._event_callbacks:
            try:
                if status == NodeStatus.RUNNING:
                    cb.on_node_started(node)
                elif status == NodeStatus.SUCCEEDED:
                    cb.on_node_succeeded(node)
                elif status in (NodeStatus.FAILED, NodeStatus.BREAKDOWN):
                    cb.on_node_failed(node)
                elif status == NodeStatus.DELETED:
                    cb.on_node_deleted(node)
            except Exception as e:  # noqa: BLE001
                logger.error("event callback %s failed: %s",
                             type(cb).__name__, e)

    # -- relaunch decision tree ----------------------------------------
    def _should_relaunch(self, node: Node) -> bool:
        """Reference: dist_job_manager.py:487-544."""
        if self.job_stage() != JobStage.RUNNING:
            return False
        if not node.relaunchable:
            return False
        if node.is_released:
            return False
        if node.exit_reason == NodeExitReason.FATAL_ERROR and \
                not self._relaunch_always:
            return False
        if node.relaunch_count >= node.max_relaunch_count:
            logger.warning("node %s exhausted relaunch budget (%d)",
                           node.name, node.max_relaunch_count)
            return False
        args = self._job_args.node_args.get(node.type)
        if args is not None and node.rank_index >= args.group_resource.count:
            # rank beyond the current target group size: this deletion is a
            # deliberate scale-down, not a failure
            return False
        return True

    def _relaunch_node(self, node: Node) -> None:
        node.is_released = True
        with self._lock:
            by_id = self._nodes[node.type]
            new_id = max(by_id) + 1
        replacement = node.get_relaunch_node(new_id)
        if node.exit_reason == NodeExitReason.OOM:
            # OOM recovery plan: same node back with more host memory
            replacement.config_resource.memory_mb = (
                node.config_resource.memory_mb * _OOM_MEMORY_FACTOR)
        elif node.exit_reason == NodeExitReason.DRAINED:
            # a graceful drain is a PLANNED departure, not a failure:
            # replace the capacity without charging the relaunch budget
            # (a job surviving N preemptions must still have its full
            # budget for real crashes)
            replacement.relaunch_count = node.relaunch_count
        with self._lock:
            by_id[new_id] = replacement
        logger.info("relaunching %s as %s (attempt %d/%d)", node.name,
                    replacement.name, replacement.relaunch_count,
                    replacement.max_relaunch_count)
        plan = ScalePlan(launch_nodes=[replacement])
        if self._job_args.remove_exited_node and \
                node.status != NodeStatus.DELETED:
            plan.remove_nodes.append(node)
        self._scaler.scale(plan)

    # -- job stage ------------------------------------------------------
    def _update_job_stage(self) -> None:
        with self._lock:
            workers = [
                n for t in (NodeType.WORKER, NodeType.CHIEF,
                            NodeType.EVALUATOR)
                for n in self._nodes.get(t, {}).values()
                if not n.is_released
            ]
            all_nodes = [n for by_id in self._nodes.values()
                         for n in by_id.values() if not n.is_released]
        if not all_nodes:
            return
        # Critical-node death without relaunch ⇒ job failed (reference:
        # dist_job_manager.py:123-125 critical-node handling).
        for node in all_nodes:
            if (node.critical
                    and node.status in (NodeStatus.FAILED,
                                        NodeStatus.BREAKDOWN)
                    and node.is_unrecoverable_failure()):
                self._fail_job(f"critical node {node.name} failed: "
                               f"{node.exit_reason}")
                return
        if workers and all(n.status == NodeStatus.SUCCEEDED
                           for n in workers):
            with self._lock:
                self._stage = JobStage.SUCCEEDED
            return
        failed = [n for n in workers
                  if n.status in (NodeStatus.FAILED, NodeStatus.BREAKDOWN)
                  and n.is_unrecoverable_failure()]
        if workers and len(failed) == len(workers):
            self._fail_job("all workers failed unrecoverably")

    def _fail_job(self, reason: str) -> None:
        with self._lock:
            if self._stage == JobStage.FAILED:
                return
            self._stage = JobStage.FAILED
            self._exit_reason = reason
        logger.error("job failed: %s", reason)

    def job_stage(self) -> str:
        with self._lock:
            return self._stage

    def exit_reason(self) -> str:
        with self._lock:
            return self._exit_reason

    # -- servicer-facing API -------------------------------------------
    def update_node_resource_usage(self, stats: msg.NodeResourceStats
                                   ) -> None:
        with self._lock:
            node = self._nodes.get(stats.node_type, {}).get(stats.node_id)
        if node is None:
            return
        node.used_resource.cpu = stats.cpu_percent
        node.used_resource.memory_mb = stats.memory_mb
        if stats.chip_stats:
            node.used_resource.chips = len(stats.chip_stats)

    def collect_heartbeat(self, node_id: int, timestamp: float,
                          node_type: str = "") -> None:
        """Refresh one node's heartbeat. node_type disambiguates groups that
        reuse ids (a worker beat must not refresh a chief/evaluator with the
        same id, which would weaken all_running_node_hanged)."""
        with self._lock:
            if node_type:
                by_id = self._nodes.get(node_type, {})
                if node_id in by_id:
                    by_id[node_id].heartbeat_time = timestamp
                    return
                # typed miss (old client, or node adopted under another
                # group after a master restart): fall through to the
                # untyped scan rather than drop the liveness signal
            for by_id in self._nodes.values():
                if node_id in by_id:
                    by_id[node_id].heartbeat_time = timestamp

    def handle_failure_report(self, report: msg.NodeFailureReport) -> None:
        with self._lock:
            node = None
            for by_id in self._nodes.values():
                if report.node_id in by_id:
                    node = by_id[report.node_id]
                    break
        if node is None:
            return
        from dlrover_tpu.common.constants import TrainingMsgLevel

        if report.restart_count >= 0:
            node.relaunch_count = max(node.relaunch_count,
                                      report.restart_count)
        if report.level == TrainingMsgLevel.NODE_ERROR:
            # Agent diagnosed a machine-level fault (e.g. TPU chip error):
            # the host must be replaced, not restarted in place.
            node.exit_reason = NodeExitReason.HARDWARE_ERROR
            node.relaunchable = True

    def handle_scale_request(self, request: msg.ScaleRequest) -> None:
        """Manual scale (reference: ScalePlanReconciler relay +
        handle in master)."""
        logger.info("manual scale: %s -> %d", request.node_type,
                    request.count)
        self.scale_node_group(request.node_type, request.count)

    def scale_node_group(self, node_type: str, count: int,
                         resource=None) -> None:
        """Resize a node group. Shrinks remove explicit top-rank victims
        marked released so their deletion events are not mistaken for
        failures and relaunched."""
        with self._lock:
            args = self._job_args.node_args.get(node_type)
            if args is None:
                return
            resource = resource or args.group_resource.node_resource
            args.group_resource.count = count
            alive = sorted(
                (n for n in self._nodes.get(node_type, {}).values()
                 if n.is_alive() and not n.is_released),
                key=lambda n: n.rank_index,
            )
        plan = ScalePlan()
        if count < len(alive):
            victims = alive[count:]
            for node in victims:
                node.relaunchable = False
                node.is_released = True
            plan.remove_nodes.extend(victims)
        # group resize both grows and catches pods the manager hasn't
        # adopted yet (the scaler trims to the target after removals)
        plan.node_group_resources[node_type] = NodeGroupResource(
            count=count, node_resource=resource)
        self._scaler.scale(plan)

    def collect_model_info(self, info: msg.ModelInfo) -> None:
        with self._lock:
            # export_state snapshots this under the same lock
            self._model_info = info

    # -- crash-consistent state (master/state_backend.py) ---------------
    def export_state(self) -> dict:
        with self._lock:
            return {
                "stage": self._stage,
                "exit_reason": self._exit_reason,
                "nodes": {
                    node_type: {str(nid): node.to_dict()
                                for nid, node in by_id.items()}
                    for node_type, by_id in self._nodes.items()
                },
                # the resource optimizer's model profile: workers report
                # ModelInfo once at loop build — a failover that lost it
                # would leave the optimizer profile-blind until the next
                # full worker restart (graftlint GL301)
                "model_info": (dataclasses.asdict(self._model_info)
                               if self._model_info else None),
            }

    def restore_state(self, state: dict) -> None:
        """Rebuild the node table (incl. restart budgets) and job stage.
        Called before start(): _init_nodes then leaves restored groups
        alone, and the watcher re-adopts any node that changed while the
        master was down through the normal event path."""
        with self._lock:
            self._stage = state.get("stage", self._stage)
            self._exit_reason = state.get("exit_reason", "")
            for node_type, by_id in state.get("nodes", {}).items():
                self._nodes[node_type] = {
                    int(nid): Node.from_dict(d)
                    for nid, d in by_id.items()
                }
            info = state.get("model_info")
            if isinstance(info, dict):
                # filter to known fields: a snapshot written by a newer
                # master must not crash an older one's restore
                known = {f.name for f in dataclasses.fields(msg.ModelInfo)}
                self._model_info = msg.ModelInfo(
                    **{k: v for k, v in info.items() if k in known})

    # -- hang detection -------------------------------------------------
    def all_running_node_hanged(self) -> bool:
        """True when every running node's heartbeat is stale (reference:
        dist_job_manager.py:692)."""
        ctx = Context.singleton()
        now = time.time()
        with self._lock:
            running = [n for by_id in self._nodes.values()
                       for n in by_id.values()
                       if n.status == NodeStatus.RUNNING]
        if not running:
            return False
        return all(
            n.heartbeat_time > 0
            and now - n.heartbeat_time > ctx.hang_seconds
            for n in running
        )

    # -- introspection ---------------------------------------------------
    def get_nodes(self, node_type: Optional[str] = None) -> List[Node]:
        with self._lock:
            if node_type is not None:
                return list(self._nodes.get(node_type, {}).values())
            return [n for by_id in self._nodes.values()
                    for n in by_id.values()]

    def get_running_workers(self) -> List[Node]:
        return [n for n in self.get_nodes(NodeType.WORKER)
                if n.status == NodeStatus.RUNNING]

    @property
    def job_args(self) -> JobArgs:
        return self._job_args


def create_job_manager(
    job_args: JobArgs,
    master_addr: str = "",
    speed_monitor=None,
    cluster=None,
) -> JobManager:
    """Wire the platform-appropriate scaler + watcher (reference:
    create_job_manager, dist_job_manager.py)."""
    if job_args.platform == PlatformType.LOCAL:
        from dlrover_tpu.master.scaler.local_scaler import LocalScaler
        from dlrover_tpu.master.watcher.local_watcher import LocalNodeWatcher
        from dlrover_tpu.scheduler.local import LocalCluster

        cluster = cluster if cluster is not None else LocalCluster()
        scaler = LocalScaler(job_args.job_name, cluster,
                             master_addr=master_addr)
        watcher = LocalNodeWatcher(cluster, job_args.job_name)
    elif job_args.platform == PlatformType.KUBERNETES:
        from dlrover_tpu.master.scaler.pod_scaler import PodScaler
        from dlrover_tpu.master.watcher.k8s_watcher import K8sPodWatcher
        from dlrover_tpu.scheduler.kubernetes import K8sClient

        client = cluster if cluster is not None else K8sClient(
            namespace=job_args.namespace)
        scaler = PodScaler(
            job_args.job_name, client, master_addr,
            image=job_args.image, command=job_args.command,
            tpu_topology=job_args.tpu_topology,
        )
        watcher = K8sPodWatcher(client, job_args.job_name)
    elif job_args.platform == PlatformType.RAY:
        from dlrover_tpu.master.scaler.ray_scaler import RayScaler
        from dlrover_tpu.master.watcher.ray_watcher import RayNodeWatcher
        from dlrover_tpu.scheduler.ray import RayClient

        client = cluster if cluster is not None else RayClient(
            job_args.job_name)
        scaler = RayScaler(job_args.job_name, client, master_addr,
                           command=job_args.command)
        watcher = RayNodeWatcher(client, job_args.job_name)
    else:
        raise ValueError(f"unsupported platform {job_args.platform!r}")
    return JobManager(job_args, scaler, watcher,
                      speed_monitor=speed_monitor)
