"""Node-event callbacks: side effects of lifecycle transitions.

Capability parity: dlrover/python/master/node/event_callback.py —
TaskRescheduleCallback (:105) requeues a dead worker's in-flight shards;
AllReduceNodeHandlingCallback (:212) maintains rendezvous membership and
the speed monitor's running-worker set.
"""

from __future__ import annotations

from typing import Dict

from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import Node


class NodeEventCallback:
    def on_node_started(self, node: Node) -> None:
        pass

    def on_node_succeeded(self, node: Node) -> None:
        pass

    def on_node_failed(self, node: Node) -> None:
        pass

    def on_node_deleted(self, node: Node) -> None:
        pass


class TaskRescheduleCallback(NodeEventCallback):
    """Requeue the doing-tasks of a dead worker so other workers pick them
    up (dynamic sharding fault tolerance)."""

    def __init__(self, task_manager):
        self._task_manager = task_manager

    def on_node_failed(self, node: Node) -> None:
        self._task_manager.recover_tasks(node.id)

    def on_node_deleted(self, node: Node) -> None:
        self._task_manager.recover_tasks(node.id)


class PsFailoverCallback(NodeEventCallback):
    """Bump the global cluster version when a state-holding node dies.

    Capability parity: TFPSNodeHandlingCallback (reference
    master/node/event_callback.py:127) driving ElasticPsService
    (elastic_training/elastic_ps.py:18): the version bump is what tells
    every worker its view of the sharded state is stale. TPU reframing:
    there are no PS processes — every worker holds embedding-table shards,
    so any state-holder death advances the version and workers reconcile
    by restoring the table from the latest committed checkpoint
    (trainer/embedding.py EmbeddingFailoverClient)."""

    def __init__(self, elastic_ps_service, node_types=("worker", "ps")):
        self._service = elastic_ps_service
        self._node_types = set(node_types)

    def _bump(self, node: Node) -> None:
        if node.type in self._node_types:
            self._service.remove_node(node.type, node.id)
            version = self._service.inc_global_cluster_version()
            logger.info(
                "state holder %s died: global cluster version -> %d",
                node.name, version,
            )

    def on_node_failed(self, node: Node) -> None:
        self._bump(node)

    def on_node_deleted(self, node: Node) -> None:
        from dlrover_tpu.common.constants import NodeStatus

        # Only an unexpected deletion of a live node is a state loss; a
        # SUCCEEDED pod's cleanup is routine, and a FAILED node already
        # bumped the version on the failure event (no double rollback).
        if node.status == NodeStatus.RUNNING:
            self._bump(node)
        elif node.type in self._node_types:
            self._service.remove_node(node.type, node.id)


class RendezvousMembershipCallback(NodeEventCallback):
    """Keep rendezvous managers' alive-node sets, the speed monitor and
    the diagnosis engine in sync with node lifecycle (the AllReduce
    path's membership bookkeeping)."""

    def __init__(self, rdzv_managers: Dict[str, object], speed_monitor,
                 diagnosis_manager=None):
        self._rdzv_managers = rdzv_managers
        self._speed_monitor = speed_monitor
        self._diagnosis_manager = diagnosis_manager

    def on_node_started(self, node: Node) -> None:
        for mgr in self._rdzv_managers.values():
            mgr.add_alive_node(node.rank_index)

    def _drop(self, node: Node, graceful: bool = False) -> None:
        for mgr in self._rdzv_managers.values():
            mgr.remove_alive_node(node.rank_index, graceful=graceful)
        from dlrover_tpu.common.constants import RendezvousName

        training = self._rdzv_managers.get(RendezvousName.TRAINING)
        live = training.alive_nodes if training is not None else set()
        # evict BOTH keys a departed node may have reported under (rank
        # for modern senders, node_id for legacy ones) so straggler
        # scores never rank dead ranks — but node.id may COLLIDE with a
        # surviving worker's rank (ids grow past the rank range on
        # relaunch), and evicting a live rank's window resets its
        # straggler evidence until the next rendezvous
        self._speed_monitor.remove_running_worker(node.rank_index)
        if node.id != node.rank_index and node.id not in live:
            self._speed_monitor.remove_running_worker(node.id)
        self._speed_monitor.reset_running_speed()
        if self._diagnosis_manager is not None:
            self._diagnosis_manager.evict_workers(live)

    def on_node_succeeded(self, node: Node) -> None:
        # A clean exit must not invalidate the cut world — survivors are
        # finishing their own work and must not be forced to restart.
        self._drop(node, graceful=True)

    def on_node_failed(self, node: Node) -> None:
        logger.info("rendezvous membership: dropping failed %s", node.name)
        self._drop(node)

    def on_node_deleted(self, node: Node) -> None:
        self._drop(node)
