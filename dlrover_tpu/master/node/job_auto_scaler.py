"""Periodic optimize → plan → scale loop.

Capability parity: JobAutoScaler (dlrover/python/master/node/
job_auto_scaler.py:73; AllreduceTrainingAutoScaler :254) — wakes every
`interval_s`, asks the optimizer for a running-stage plan, converts it to a
ScalePlan within spec limits, and actuates through the job manager's
scaler. OOM relaunch resizing is handled inline by the job manager; this
loop owns throughput-driven worker-count changes and hot-host tuning.
"""

from __future__ import annotations

import threading
from typing import Optional

from dlrover_tpu import obs
from dlrover_tpu.common.constants import NodeType
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import NodeGroupResource
from dlrover_tpu.master.resource.optimizer import (
    OptimizeStage,
    ResourceLimits,
    ResourceOptimizer,
)
from dlrover_tpu.master.scaler.base import ScalePlan


class JobAutoScaler:
    def __init__(
        self,
        job_manager,
        optimizer: ResourceOptimizer,
        speed_monitor=None,
        limits: Optional[ResourceLimits] = None,
        interval_s: float = 60.0,
    ):
        self._job_manager = job_manager
        self._optimizer = optimizer
        self._speed_monitor = speed_monitor
        self._limits = limits or ResourceLimits()
        self._interval_s = interval_s
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # the tuned-knob compare-and-publish runs on the scaler thread
        # and from direct execute_job_optimization() callers
        self._tuned_lock = threading.Lock()
        self.paral_config_version = 0
        self.suggested_dataloader_workers = 0
        # callable(**fields) merging tuned knobs into the published config
        # (wired to MasterServicer.merge_paral_config)
        self.paral_config_sink = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="auto-scaler")
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.execute_job_optimization()
            except Exception as e:  # noqa: BLE001 - loop must survive
                logger.error("auto-scale round failed: %s", e)

    # -- one optimization round ----------------------------------------
    def execute_job_optimization(self) -> Optional[ScalePlan]:
        """One optimize → plan → actuate round, timed as a
        `scale_decision` span (outcome attr: noop / tuned / scaled)."""
        with obs.span("scale_decision") as decision:
            plan = self._execute_job_optimization(decision)
        outcome = decision.attrs.get("outcome", "noop")
        obs.get_registry().counter(
            "dlrover_tpu_scale_decisions_total",
            "Auto-scaler optimization rounds by outcome",
            labelnames=("outcome",),
        ).labels(outcome=outcome).inc()
        return plan

    def _execute_job_optimization(self, decision) -> Optional[ScalePlan]:
        if self._speed_monitor is not None:
            self._optimizer.stats.add_speed_sample(
                len(self._job_manager.get_running_workers()),
                self._speed_monitor.running_speed(),
            )
        worker_args = self._job_manager.job_args.worker_args()
        if worker_args is None or not worker_args.auto_scale:
            return None
        current = worker_args.group_resource.count
        max_count = worker_args.max_count or self._limits.max_nodes or current
        plan = self._optimizer.generate_plan(
            OptimizeStage.RUNNING,
            {"worker_count": current, "max_worker_count": max_count},
        )
        plan.limit(self._limits)
        with self._tuned_lock:
            tuned = (plan.dataloader_workers
                     and plan.dataloader_workers
                     != self.suggested_dataloader_workers)
            if tuned:
                self.suggested_dataloader_workers = plan.dataloader_workers
                self.paral_config_version += 1
        if tuned:
            decision.set_attr("outcome", "tuned")
            if self.paral_config_sink is not None:
                self.paral_config_sink(
                    dataloader_workers=plan.dataloader_workers,
                    dataloader_batch_size=plan.dataloader_batch_size,
                )
        if plan.empty():
            return None
        scale_plan = ScalePlan()
        for node_type, group in plan.node_group_resources.items():
            if group.count <= 0 or group.count == current:
                continue
            resource = (group.node_resource
                        if group.node_resource.memory_mb
                        else worker_args.group_resource.node_resource)
            scale_plan.node_group_resources[node_type] = NodeGroupResource(
                count=group.count, node_resource=resource)
            if node_type == NodeType.WORKER:
                worker_args.group_resource.count = group.count
        if scale_plan.empty():
            return None
        counts = {t: g.count
                  for t, g in scale_plan.node_group_resources.items()}
        logger.info("auto-scale plan: %s", counts)
        decision.set_attr("outcome", "scaled")
        decision.set_attr("plan", counts)
        obs.get_flight_recorder().record_event("scale_plan", **counts)
        for node_type, group in scale_plan.node_group_resources.items():
            self._job_manager.scale_node_group(node_type, group.count,
                                               group.node_resource)
        return scale_plan
