"""Obs-catalog drift checker (GL6xx): docs ↔ code, both directions.

``docs/observability.md`` is the operator contract: its metric catalog,
span taxonomy and flight-event catalog tables claim what the fleet
emits, and ``obs/tsdb.DASHBOARD_SERIES`` claims what ``tools/top.py``
can render. PR 11's sixth review pass caught a ``DASHBOARD_SERIES``
entry that nothing fed; this checker makes that a lint failure instead:

GL601  a documented metric/span/flight-event that no code registers,
       ingests or emits (the code lost it, or the docs invented it).
GL602  an emitted metric/span/flight-event with no catalog row.
GL603  a ``DASHBOARD_SERIES`` entry no metric registration or tsdb
       ingest backs — the dashboard column renders empty forever.

Like the protocol pass this is cross-artifact: the per-file half
(:func:`extract_obs_facts`) records every constant-name emission site —
``registry.counter/gauge/histogram("name", …)``, ``store.ingest("name",
…)``, ``obs.span("name", …)`` / ``record_span("name", …)``,
``record_event("name", …)`` and the ``DASHBOARD_SERIES`` tuple — and is
cached by the runner; the project half (:func:`check_obs_catalog`)
parses the markdown tables and diffs. Dynamic names (a variable first
argument) are invisible by design: the replay paths
(``registry.counter(sample.name)``) re-emit names some original
constant site already declared.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from dlrover_tpu.analysis.findings import Finding

TSDB_SUFFIX = "obs/tsdb.py"

_METRIC_METHODS = {"counter", "gauge", "histogram"}
_SPAN_FUNCS = {"span", "record_span"}
_EVENT_METHODS = {"record_event"}

# markdown section headings → catalog kinds (case-insensitive substring)
_SECTIONS = (
    ("metric catalog", "metric"),
    ("span taxonomy", "span"),
    ("flight-event catalog", "event"),
)
_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_.:*-]+)`")


def _first_str_arg(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) and \
            isinstance(node.args[0].value, str):
        return node.args[0].value
    for kw in node.keywords:
        if kw.arg == "name" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


def _src(source_lines: Sequence[str], lineno: int) -> str:
    if 1 <= lineno <= len(source_lines):
        return source_lines[lineno - 1]
    return ""


def extract_obs_facts(relpath: str, tree: ast.Module,
                      source_lines: Sequence[str]) -> Dict:
    """Constant-name observability emission sites in one module:
    ``{"metric"|"span"|"event"|"dashboard": [[name, line, srcline]…]}``."""
    out: Dict[str, List[List]] = {
        "metric": [], "span": [], "event": [], "dashboard": []}
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            func = node.func
            name = _first_str_arg(node)
            if name is None:
                continue
            if isinstance(func, ast.Attribute):
                if func.attr in _METRIC_METHODS or func.attr == "ingest":
                    out["metric"].append(
                        [name, node.lineno, _src(source_lines,
                                                 node.lineno)])
                elif func.attr in _SPAN_FUNCS:
                    out["span"].append(
                        [name, node.lineno, _src(source_lines,
                                                 node.lineno)])
                elif func.attr in _EVENT_METHODS:
                    out["event"].append(
                        [name, node.lineno, _src(source_lines,
                                                 node.lineno)])
            elif isinstance(func, ast.Name) and func.id in _SPAN_FUNCS:
                out["span"].append(
                    [name, node.lineno, _src(source_lines,
                                             node.lineno)])
        elif isinstance(node, ast.Assign) and relpath.endswith(
                TSDB_SUFFIX):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and \
                        tgt.id == "DASHBOARD_SERIES" and isinstance(
                        node.value, (ast.Tuple, ast.List)):
                    for el in node.value.elts:
                        if isinstance(el, ast.Constant) and isinstance(
                                el.value, str):
                            out["dashboard"].append(
                                [el.value, el.lineno,
                                 _src(source_lines, el.lineno)])
    return {k: v for k, v in out.items() if v}


def parse_catalog(doc_text: str) -> Dict[str, Dict[str, Tuple[int, str]]]:
    """Markdown catalogs: kind → {name: (line, row_text)}. A section is
    a ``##`` heading containing one of the known titles; rows are table
    lines whose first cell is a backticked name."""
    catalogs: Dict[str, Dict[str, Tuple[int, str]]] = {
        kind: {} for _, kind in _SECTIONS}
    current: Optional[str] = None
    for i, line in enumerate(doc_text.splitlines(), start=1):
        if line.startswith("##"):
            lowered = line.lower()
            current = None
            for title, kind in _SECTIONS:
                if title in lowered:
                    current = kind
                    break
            continue
        if current is None:
            continue
        m = _ROW_RE.match(line.strip())
        if m:
            catalogs[current].setdefault(m.group(1),
                                         (i, line.strip()))
    return catalogs


def check_obs_catalog(
        doc_relpath: str, doc_text: str,
        facts_by_path: Dict[str, Dict]
) -> List[Tuple[Finding, str]]:
    """Diff the doc catalogs against the pooled emission facts. Returns
    (finding, source_line) pairs like the protocol checker."""
    catalogs = parse_catalog(doc_text)
    emitted: Dict[str, Dict[str, Tuple[str, int, str]]] = {
        "metric": {}, "span": {}, "event": {}}
    dashboard: List[Tuple[str, str, int, str]] = []
    for path in sorted(facts_by_path):
        obs = facts_by_path[path].get("obs") or {}
        for kind in emitted:
            for name, line, srcline in obs.get(kind, ()):
                emitted[kind].setdefault(name, (path, line, srcline))
        for name, line, srcline in obs.get("dashboard", ()):
            dashboard.append((name, path, line, srcline))

    out: List[Tuple[Finding, str]] = []
    # -- GL601: documented, never emitted -------------------------------
    for kind in ("metric", "span", "event"):
        for name, (line, row) in sorted(catalogs[kind].items()):
            if name in emitted[kind]:
                continue
            out.append((Finding(
                "GL601", doc_relpath, line, 0,
                f"documented {kind} `{name}` is not emitted anywhere "
                f"in the package", symbol=name), row))
    # -- GL602: emitted, never documented -------------------------------
    for kind in ("metric", "span", "event"):
        for name, (path, line, srcline) in sorted(
                emitted[kind].items()):
            if name in catalogs[kind]:
                continue
            out.append((Finding(
                "GL602", path, line, 0,
                f"{kind} `{name}` is emitted here but has no "
                f"{doc_relpath} catalog row", symbol=name), srcline))
    # -- GL603: dashboard series without a feed -------------------------
    for name, path, line, srcline in sorted(dashboard):
        if name in emitted["metric"]:
            continue
        out.append((Finding(
            "GL603", path, line, 0,
            f"DASHBOARD_SERIES entry `{name}` has no metric "
            f"registration or tsdb ingest backing it", symbol=name),
            srcline))
    return out
