"""graftrace contract passes: fence discipline + staleness discipline.

GL703 *fence discipline* — every class that writes under the master
state dir (a ``state_dir``/``directory`` constructor param plus file
writes: the snapshot backend, mutation log, tsdb sidecar, and any
future writer) must consult the fence gate on its write path —
``self.gate``/a ``gate`` parameter/``_check_fenced`` — and every
construction site of an attribute-gated writer must wire ``.gate``.
PRs 10/11 retrofitted the gate onto each writer by review; this rule
makes the next state-dir artifact fenced by construction.  The per-file
half extracts facts; :func:`check_fence` pools them cross-module
(writers live in ``state_backend.py``/``tsdb.py``, construction sites
in ``job_master.py``).

GL704 *staleness discipline* — per file: a hot-KV key literal
(``dcn/``/``coord/`` prefixes, the gradient-path namespace) built
inside a function must embed an epoch/round/generation segment, or the
function must handle the token itself (the ``_ns()`` helper pattern);
and a function that parses a stamped plan payload
(``json.loads(...plan_json...)``) must reference the epoch/generation
stamp it validates against.  The PR 7 stale-restore-plan and PR 8
stale-rejoin bugs are both instances of this rule.

The hot prefixes are single-sourced in ``common/constants.py``
(``HOT_KV_PREFIXES``); the copy here is asserted equal by
``tests/test_graftrace.py`` so the two cannot drift (the analyzer must
stay importable without the package's runtime deps).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.analysis.findings import Finding
from dlrover_tpu.analysis.trace_safety import (
    _dotted_name,
    _import_aliases,
)

# mirror of dlrover_tpu.common.constants.HOT_KV_PREFIXES (drift-checked
# by tests/test_graftrace.py::test_hot_prefixes_match_constants)
HOT_KV_PREFIXES = ("dcn/", "coord/")

_TOKEN_RE = re.compile(r"epoch|generation|round|token|stamp", re.I)
_WRITE_MODE_RE = re.compile(r"[wax+]")
_STATE_DIR_PARAM_RE = re.compile(r"state_?dir")
_FENCED_ROOTS = ("master/", "obs/")


def _subtree_names(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id)
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr)
    return out


def _has_token(names: Set[str]) -> bool:
    return any(_TOKEN_RE.search(n) for n in names)


# -- GL704: per-file staleness pass -----------------------------------------

class StalenessPass:
    def run(self, relpath: str, tree: ast.Module,
            source_lines: Sequence[str]) -> List[Finding]:
        aliases = _import_aliases(tree)
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # only OUTERMOST functions: a nested def shares its
                # parent's token scope (closures see the epoch var)
                node._graft_outer = True            # type: ignore
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(node):
                if sub is not node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    sub._graft_outer = False        # type: ignore
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and getattr(node, "_graft_outer", False):
                findings.extend(self._check_function(
                    relpath, node, aliases))
        return findings

    def _check_function(self, relpath: str, fn: ast.AST,
                        aliases: Dict[str, str]) -> List[Finding]:
        names = _subtree_names(fn)
        has_token = _has_token(names)
        findings: List[Finding] = []
        in_fstring: Set[int] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.JoinedStr):
                for v in node.values:
                    in_fstring.add(id(v))
        docstrings = {id(stmt.value)
                      for sub in ast.walk(fn)
                      if isinstance(sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.ClassDef, ast.Module))
                      for stmt in sub.body[:1]
                      if isinstance(stmt, ast.Expr)
                      and isinstance(stmt.value, ast.Constant)}

        for node in ast.walk(fn):
            head = ""
            namespaced = False
            if isinstance(node, ast.JoinedStr):
                for v in node.values:
                    if isinstance(v, ast.Constant) and isinstance(
                            v.value, str):
                        head += v.value
                    else:
                        break
                namespaced = any(
                    _has_token(_subtree_names(v))
                    for v in node.values
                    if isinstance(v, ast.FormattedValue))
            elif isinstance(node, ast.Constant) and isinstance(
                    node.value, str):
                if id(node) in in_fstring or id(node) in docstrings:
                    continue
                head = node.value
            else:
                continue
            prefix = next((p for p in HOT_KV_PREFIXES
                           if head.startswith(p)), None)
            # a bare-prefix literal is a prefix CHECK (startswith),
            # not a key — only a longer literal names an actual key
            if prefix is None or head == prefix:
                continue
            if namespaced or has_token:
                continue
            findings.append(Finding(
                "GL704", relpath, node.lineno, node.col_offset,
                f"hot-KV key '{head}…' has no epoch/round/generation "
                f"segment and the enclosing function never touches a "
                f"staleness token — a stale payload from the previous "
                f"world can be consumed silently",
                symbol=getattr(fn, "name", "")))

        if not has_token:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if _dotted_name(node.func, aliases) != "json.loads":
                    continue
                if not node.args:
                    continue
                arg_names = _subtree_names(node.args[0])
                if not any("plan" in n.lower() for n in arg_names):
                    continue
                findings.append(Finding(
                    "GL704", relpath, node.lineno, node.col_offset,
                    "stamped plan parsed without validating (or "
                    "propagating) its epoch/generation token — a plan "
                    "computed for the previous world must not commit",
                    symbol=getattr(fn, "name", "")))
        return findings


# -- GL703: fence-discipline facts + pooled check ---------------------------

def _is_write_open(node: ast.Call, aliases: Dict[str, str]) -> bool:
    head = _dotted_name(node.func, aliases)
    if head != "open":
        return False
    mode = None
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
        mode = node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and bool(_WRITE_MODE_RE.search(mode))


def extract_fence_facts(relpath: str, tree: ast.Module,
                        source_lines: Sequence[str]) -> Dict:
    """Per-file facts for the pooled GL703 checker."""
    aliases = _import_aliases(tree)

    def _src(line: int) -> str:
        if 1 <= line <= len(source_lines):
            return source_lines[line - 1]
        return ""

    writers: List[Dict] = []
    for node in tree.body:
        if not isinstance(node, ast.ClassDef):
            continue
        init = next((m for m in node.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            continue
        params = [a.arg for a in init.args.args[1:]]
        has_state_dir = any(_STATE_DIR_PARAM_RE.search(p)
                            for p in params)
        if not has_state_dir and relpath.startswith(_FENCED_ROOTS):
            has_state_dir = "directory" in params
        if not has_state_dir:
            continue
        write_sites: List[Dict] = []
        consults = False
        gate_attr = False
        for meth in node.body:
            if not isinstance(meth, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            names = _subtree_names(meth)
            if "gate" in names or "_check_fenced" in names:
                consults = True
            for sub in ast.walk(meth):
                if meth.name == "__init__":
                    if (isinstance(sub, ast.Attribute)
                            and sub.attr == "gate"
                            and isinstance(sub.ctx, ast.Store)):
                        gate_attr = True
                    continue
                if isinstance(sub, ast.Call):
                    head = _dotted_name(sub.func, aliases)
                    if head in ("os.replace", "os.rename") or \
                            _is_write_open(sub, aliases):
                        write_sites.append({
                            "line": sub.lineno, "col": sub.col_offset,
                            "srcline": _src(sub.lineno),
                            "symbol": f"{node.name}.{meth.name}"})
        if write_sites:
            # ast.walk is breadth-first: sort so the finding anchors
            # at the FIRST write site in source order
            write_sites.sort(key=lambda s: (s["line"], s["col"]))
            writers.append({"cls": node.name,
                            "write_sites": write_sites,
                            "consults_gate": consults,
                            "gate_attr": gate_attr,
                            "gate_param": "gate" in params})

    ctors: List[Dict] = []
    gate_wired: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Attribute) and tgt.attr == "gate":
                    base = tgt.value
                    if isinstance(base, ast.Attribute) and isinstance(
                            base.value, ast.Name):
                        gate_wired.add(f"{base.value.id}.{base.attr}")
                    elif isinstance(base, ast.Name):
                        gate_wired.add(base.id)
            if isinstance(node.value, ast.Call):
                head = _dotted_name(node.value.func, aliases) or ""
                cls = head.rsplit(".", 1)[-1]
                arg_names: Set[str] = set()
                for arg in node.value.args:
                    arg_names |= _subtree_names(arg)
                for kw in node.value.keywords:
                    arg_names |= _subtree_names(kw.value)
                # only dir-taking constructions can be state-dir
                # writers — keeps the pooled fact payload small
                dir_arg = any("dir" in n.lower() for n in arg_names)
                if cls[:1].isupper() and dir_arg:
                    bound = ""
                    tgt = node.targets[0]
                    if isinstance(tgt, ast.Attribute) and isinstance(
                            tgt.value, ast.Name):
                        bound = f"{tgt.value.id}.{tgt.attr}"
                    elif isinstance(tgt, ast.Name):
                        bound = tgt.id
                    has_gate_kwarg = any(
                        kw.arg == "gate" for kw in node.value.keywords)
                    ctors.append({"cls": cls, "bound": bound,
                                  "line": node.value.lineno,
                                  "col": node.value.col_offset,
                                  "srcline": _src(node.value.lineno),
                                  "gate_kwarg": has_gate_kwarg})

    if not writers and not ctors:
        return {}
    return {"writers": writers, "ctors": ctors,
            "gate_wired": sorted(gate_wired)}


def check_fence(
        facts_by_path: Dict[str, Dict]) -> List[Tuple[Finding, str]]:
    out: List[Tuple[Finding, str]] = []
    attr_gated: Set[str] = set()
    for path, facts in sorted(facts_by_path.items()):
        fence = (facts or {}).get("fence") or {}
        for w in fence.get("writers", ()):
            if w["consults_gate"]:
                if w.get("gate_attr"):
                    attr_gated.add(w["cls"])
                continue
            site = w["write_sites"][0]
            out.append((Finding(
                "GL703", path, site["line"], site["col"],
                f"state-dir writer {w['cls']} never consults the fence "
                f"gate on its write path — a deposed master keeps "
                f"writing over the promoted one's state (wire a "
                f"`gate` callable like MutationLog/TsdbCollector do)",
                symbol=site["symbol"]), site["srcline"]))
    for path, facts in sorted(facts_by_path.items()):
        fence = (facts or {}).get("fence") or {}
        wired = set(fence.get("gate_wired", ()))
        for c in fence.get("ctors", ()):
            if c["cls"] not in attr_gated:
                continue
            if c.get("gate_kwarg") or c["bound"] in wired:
                continue
            out.append((Finding(
                "GL703", path, c["line"], c["col"],
                f"{c['cls']} is constructed here but its fence gate is "
                f"never wired ({c['bound'] or 'the instance'}.gate "
                f"stays None) — the writer runs unfenced",
                symbol=""), c["srcline"]))
    return out
