"""Pass 4: cross-module protocol-symmetry analysis (GL4xx).

The control-plane protocol lives in four places that must agree:
``common/messages.py`` (the dataclass vocabulary), ``master/servicer.py``
+ ``master/coord_service.py`` (the dispatch side), and
``agent/master_client.py`` (the typed wrappers). PR 10's
``HOT_KV_PREFIXES`` single-sourcing exists because a contract changed on
one side only; this pass proves three symmetries mechanically:

GL401  a message field read on one side but never set at any
       construction site on the other (the reader only ever sees the
       dataclass default), and the reverse — a field set at
       construction that nothing anywhere reads.
GL402  a request type the servicer dispatches with no MasterClient
       wrapper constructing it (the endpoint is unreachable from
       agents/tools), or a client-sent type no servicer dispatches
       (the wrapper can only ever get "unknown request").
GL403  a string literal in a protocol module that equals a
       ``common/constants.py`` contract value (KV prefixes, env-var
       names, rendezvous names) instead of importing the constant.

Unlike the other passes this one is interprocedural ACROSS FILES: the
per-file half (:func:`extract_protocol_facts`) distills each module into
a small JSON-serializable fact record (cached by the runner alongside
findings), and the project half (:func:`check_protocol`) diffs the
records. Evidence rules are deliberately conservative — reads bind to a
message class only through ``isinstance`` guards, parameter annotations,
construction assignments and ``_get_typed``-style expected-type calls;
everything else (``x.field`` on an unknown object, ``getattr`` with a
constant name) counts as a WEAK read that can suppress a "never read"
finding but never raise one. A class constructed with positional args,
``*``/``**`` splats or ``dataclasses.replace`` is treated as fully set.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.analysis.findings import Finding

# relpath suffixes → module roles (fixture packages mirror the layout)
MESSAGES_SUFFIX = "common/messages.py"
SERVER_SUFFIXES = ("master/servicer.py", "master/coord_service.py")
CLIENT_SUFFIX = "agent/master_client.py"
CONSTANTS_SUFFIX = "common/constants.py"
# modules whose string literals are checked against the contract
# (GL403): the protocol modules plus the KV store, which implements the
# hot-prefix contract the constants single-source
LITERAL_SUFFIXES = SERVER_SUFFIXES + (
    MESSAGES_SUFFIX, CLIENT_SUFFIX, "master/kv_store.py")

# calls whose bare message-class argument types their result
_EXPECTED_TYPE_CALLS = {"_get_typed", "_report_typed", "_typed",
                        "deserialize_expecting"}


def _has_role(relpath: str, suffixes) -> bool:
    if isinstance(suffixes, str):
        suffixes = (suffixes,)
    return any(relpath == s or relpath.endswith("/" + s)
               for s in suffixes)


def _line(source_lines: Sequence[str], lineno: int) -> str:
    if 1 <= lineno <= len(source_lines):
        return source_lines[lineno - 1]
    return ""


def _msg_class_name(expr: ast.AST) -> Optional[str]:
    """``msg.X`` / bare ``X`` (capitalized) → "X"; anything else None."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value,
                                                      ast.Name):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    else:
        return None
    return name if name[:1].isupper() else None


def _contract_worthy(value: str) -> bool:
    """Distinctive contract strings only — generic words ("worker",
    "running") would drown the pass in incidental matches."""
    return len(value) >= 4 and any(c in value for c in "/-_")


class _FactVisitor(ast.NodeVisitor):
    """One walk collecting every evidence kind; class bindings for
    local names are maintained as a scope stack keyed per function."""

    def __init__(self, relpath: str, source_lines: Sequence[str],
                 facts: Dict):
        self.relpath = relpath
        self.lines = source_lines
        self.facts = facts
        self._bindings: List[Dict[str, str]] = [{}]

    # -- binding helpers ---------------------------------------------------
    def _bind(self, name: str, cls: str) -> None:
        self._bindings[-1][name] = cls

    def _lookup(self, name: str) -> Optional[str]:
        for frame in reversed(self._bindings):
            if name in frame:
                return frame[name]
        return None

    # -- scopes ------------------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._enter_function(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._enter_function(node)

    def _enter_function(self, node) -> None:
        self._bindings.append({})
        for arg in node.args.posonlyargs + node.args.args + \
                node.args.kwonlyargs:
            if arg.annotation is not None:
                cls = _msg_class_name(arg.annotation)
                if cls:
                    self._bind(arg.arg, cls)
        self.generic_visit(node)
        self._bindings.pop()

    def visit_If(self, node: ast.If) -> None:
        """``if isinstance(request, msg.X):`` binds request→X in the
        body (the servicer's dispatch idiom)."""
        self.visit(node.test)
        bound = self._isinstance_binding(node.test)
        if bound is not None:
            name, cls = bound
            self._bindings.append({name: cls})
            for stmt in node.body:
                self.visit(stmt)
            self._bindings.pop()
        else:
            for stmt in node.body:
                self.visit(stmt)
        for stmt in node.orelse:
            self.visit(stmt)

    def _isinstance_binding(
            self, test: ast.AST) -> Optional[Tuple[str, str]]:
        if (isinstance(test, ast.Call)
                and isinstance(test.func, ast.Name)
                and test.func.id == "isinstance"
                and len(test.args) == 2
                and isinstance(test.args[0], ast.Name)):
            cls = _msg_class_name(test.args[1])
            if cls:
                self._record_dispatch(cls, test)
                return test.args[0].id, cls
            # isinstance against a tuple still counts as dispatch
            if isinstance(test.args[1], ast.Tuple):
                for el in test.args[1].elts:
                    sub = _msg_class_name(el)
                    if sub:
                        self._record_dispatch(sub, test)
        return None

    def _record_dispatch(self, cls: str, node: ast.AST) -> None:
        self.facts["dispatch"].setdefault(cls, []).append(
            [node.lineno, node.col_offset,
             _line(self.lines, node.lineno)])

    # -- constructions / typed calls / assignments -------------------------
    def visit_Assign(self, node: ast.Assign) -> None:
        cls = self._value_class(node.value)
        if cls:
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self._bind(tgt.id, cls)
        self.generic_visit(node)

    def _value_class(self, value: ast.AST) -> Optional[str]:
        """The message class a value expression produces, if knowable:
        a construction ``msg.X(...)`` or an expected-type call."""
        if not isinstance(value, ast.Call):
            return None
        cls = _msg_class_name(value.func)
        if cls:
            return cls
        if isinstance(value.func, ast.Attribute) and \
                value.func.attr in _EXPECTED_TYPE_CALLS:
            expected = None
            for arg in list(value.args) + [kw.value
                                           for kw in value.keywords]:
                if not isinstance(arg, ast.Call):
                    sub = _msg_class_name(arg)
                    if sub:
                        expected = sub
            return expected
        return None

    def visit_Call(self, node: ast.Call) -> None:
        cls = _msg_class_name(node.func)
        if cls:
            kwargs = [kw.arg for kw in node.keywords if kw.arg]
            opaque = bool(node.args) or any(
                kw.arg is None for kw in node.keywords)
            self.facts["constructions"].setdefault(cls, []).append(
                [node.lineno, node.col_offset, sorted(kwargs),
                 opaque, _line(self.lines, node.lineno)])
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr == "replace":
            # dataclasses.replace(current, ...): treat the bound class
            # of the first arg (if known) as opaquely constructed
            if node.args and isinstance(node.args[0], ast.Name):
                bound = self._lookup(node.args[0].id)
                if bound:
                    self.facts["constructions"].setdefault(
                        bound, []).append(
                        [node.lineno, node.col_offset, [], True,
                         _line(self.lines, node.lineno)])
        elif isinstance(node.func, ast.Name) and \
                node.func.id == "getattr" and len(node.args) >= 2 and \
                isinstance(node.args[1], ast.Constant) and \
                isinstance(node.args[1].value, str):
            field = node.args[1].value
            if node.args and isinstance(node.args[0], ast.Name):
                bound = self._lookup(node.args[0].id)
                if bound:
                    self.facts["reads"].setdefault(bound, []).append(
                        [field, node.lineno, node.col_offset,
                         _line(self.lines, node.lineno)])
                else:
                    self.facts["weak_reads"].append(field)
            else:
                self.facts["weak_reads"].append(field)
        self.generic_visit(node)

    # -- attribute reads ---------------------------------------------------
    def visit_Attribute(self, node: ast.Attribute) -> None:
        if isinstance(node.ctx, ast.Load):
            if isinstance(node.value, ast.Name):
                bound = self._lookup(node.value.id)
                if bound:
                    self.facts["reads"].setdefault(bound, []).append(
                        [node.attr, node.lineno, node.col_offset,
                         _line(self.lines, node.lineno)])
                elif node.value.id not in ("self", "cls"):
                    self.facts["weak_reads"].append(node.attr)
            elif isinstance(node.value, ast.Call):
                # chained read on a typed call: _get_typed(..., msg.X).f
                cls = self._value_class(node.value)
                if cls:
                    self.facts["reads"].setdefault(cls, []).append(
                        [node.attr, node.lineno, node.col_offset,
                         _line(self.lines, node.lineno)])
                else:
                    self.facts["weak_reads"].append(node.attr)
            else:
                self.facts["weak_reads"].append(node.attr)
        self.generic_visit(node)


def _collect_message_fields(tree: ast.Module) -> Dict[str, List[str]]:
    """Dataclass field vocabulary: annotated class-body assignments of
    classes (transitively) deriving from the module's Message base."""
    classes = {n.name: n for n in tree.body
               if isinstance(n, ast.ClassDef)}

    def is_message(name: str, seen: Set[str]) -> bool:
        if name == "Message":
            return True
        node = classes.get(name)
        if node is None or name in seen:
            return False
        return any(
            isinstance(b, ast.Name) and is_message(b.id, seen | {name})
            for b in node.bases)

    out: Dict[str, List[str]] = {}
    for name, node in classes.items():
        if name == "Message" or not is_message(name, set()):
            continue
        fields = []
        for item in node.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name):
                fields.append(item.target.id)
        out[name] = fields
    return out


def _collect_contract_constants(tree: ast.Module) -> Dict[str, str]:
    """value → qualified constant name for the single-sourced contract
    strings: class-attribute strings and module-level tuple elements."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, ast.Assign) and isinstance(
                        item.value, ast.Constant) and isinstance(
                        item.value.value, str):
                    value = item.value.value
                    if _contract_worthy(value):
                        for tgt in item.targets:
                            if isinstance(tgt, ast.Name):
                                out.setdefault(
                                    value, f"{node.name}.{tgt.id}")
        elif isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Tuple, ast.List)):
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                for el in node.value.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                            el.value, str) and _contract_worthy(el.value):
                        out.setdefault(el.value, tgt.id)
    return out


def _collect_literals(tree: ast.Module,
                      source_lines: Sequence[str]) -> List[List]:
    """Standalone string constants in expressions (docstrings and
    standalone-Expr strings excluded)."""
    docstrings = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant):
                docstrings.add(id(body[0].value))
    out: List[List] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(
                node.value, str) and id(node) not in docstrings:
            if _contract_worthy(node.value):
                out.append([node.value, node.lineno, node.col_offset,
                            _line(source_lines, node.lineno)])
    return out


def extract_protocol_facts(relpath: str, tree: ast.Module,
                           source_lines: Sequence[str]) -> Dict:
    """The per-file half: a JSON-serializable fact record the runner
    caches beside the file's findings."""
    facts: Dict = {
        "constructions": {}, "reads": {}, "weak_reads": [],
        "dispatch": {},
    }
    visitor = _FactVisitor(relpath, source_lines, facts)
    visitor.visit(tree)
    facts["weak_reads"] = sorted(set(facts["weak_reads"]))
    if _has_role(relpath, CLIENT_SUFFIX):
        # every message-class NAME the client module references —
        # `msg.X` attribute style AND directly-imported bare names
        # (constructions, annotations, expected-type args): a wrapper
        # may take the message as a typed parameter instead of
        # constructing it — that still reaches the endpoint. Bare-name
        # collection is deliberately broad (any capitalized loaded
        # name): refs only SUPPRESS GL402, and a non-message name can
        # never match a dispatched message class by accident.
        refs = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name):
                cls = _msg_class_name(node)
                if cls:
                    refs.add(cls)
            elif isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load) and node.id[:1].isupper():
                refs.add(node.id)
        facts["class_refs"] = sorted(refs)
    if _has_role(relpath, MESSAGES_SUFFIX):
        facts["message_fields"] = _collect_message_fields(tree)
    if _has_role(relpath, CONSTANTS_SUFFIX):
        facts["contract_constants"] = _collect_contract_constants(tree)
    if _has_role(relpath, LITERAL_SUFFIXES):
        facts["literals"] = _collect_literals(tree, source_lines)
    facts["roles"] = {
        "messages": _has_role(relpath, MESSAGES_SUFFIX),
        "server": _has_role(relpath, SERVER_SUFFIXES),
        "client": _has_role(relpath, CLIENT_SUFFIX),
    }
    return facts


def check_protocol(
        facts_by_path: Dict[str, Dict]
) -> List[Tuple[Finding, str]]:
    """The project half: diff the per-file fact records. Returns
    (finding, source_line) pairs — the caller fingerprints and applies
    that file's pragmas."""
    message_fields: Dict[str, Set[str]] = {}
    for facts in facts_by_path.values():
        for cls, fields in (facts.get("message_fields") or {}).items():
            message_fields.setdefault(cls, set()).update(fields)
    if not message_fields:
        return []        # no message vocabulary in the analyzed roots

    # pooled evidence across every analyzed module
    set_fields: Dict[str, Set[str]] = {}
    opaque_classes: Set[str] = set()
    constructions: Dict[str, List[Tuple[str, List]]] = {}
    reads: Dict[str, List[Tuple[str, List]]] = {}
    weak_reads: Set[str] = set()
    dispatch: Dict[str, List[Tuple[str, List]]] = {}
    client_sent: Dict[str, List[Tuple[str, List]]] = {}
    client_refs: Set[str] = set()
    contract: Dict[str, str] = {}
    literals: List[Tuple[str, List]] = []

    for path, facts in sorted(facts_by_path.items()):
        roles = facts.get("roles") or {}
        for cls, sites in (facts.get("constructions") or {}).items():
            if cls not in message_fields:
                continue
            for site in sites:
                constructions.setdefault(cls, []).append((path, site))
                set_fields.setdefault(cls, set()).update(site[2])
                if site[3]:
                    opaque_classes.add(cls)
                if roles.get("client"):
                    client_sent.setdefault(cls, []).append((path, site))
        for cls, sites in (facts.get("reads") or {}).items():
            if cls not in message_fields:
                continue
            for site in sites:
                reads.setdefault(cls, []).append((path, site))
        weak_reads.update(facts.get("weak_reads") or ())
        client_refs.update(facts.get("class_refs") or ())
        if roles.get("server"):
            for cls, sites in (facts.get("dispatch") or {}).items():
                if cls not in message_fields:
                    continue
                for site in sites:
                    dispatch.setdefault(cls, []).append((path, site))
        contract.update(facts.get("contract_constants") or {})
        for lit in facts.get("literals") or ():
            literals.append((path, lit))

    out: List[Tuple[Finding, str]] = []

    # -- GL401: read but never set --------------------------------------
    for cls in sorted(reads):
        if cls not in constructions or cls in opaque_classes:
            continue      # nothing constructs it here / can't enumerate
        for path, (field, line, col, srcline) in sorted(reads[cls]):
            if field not in message_fields[cls]:
                continue  # property / method access, not a field
            if field in set_fields.get(cls, ()):
                continue
            out.append((Finding(
                "GL401", path, line, col,
                f"{cls}.{field} is read here but never set at any "
                f"construction site — the reader only ever sees the "
                f"dataclass default", symbol=f"{cls}.{field}"),
                srcline))

    # -- GL401: set but never read --------------------------------------
    read_fields: Dict[str, Set[str]] = {}
    for cls, sites in reads.items():
        read_fields.setdefault(cls, set()).update(
            site[0] for _, site in sites)
    for cls in sorted(constructions):
        strong = read_fields.get(cls, set())
        for path, (line, col, kwargs, opaque, srcline) in sorted(
                constructions[cls]):
            for field in kwargs:
                if field in strong or field in weak_reads:
                    continue
                out.append((Finding(
                    "GL401", path, line, col,
                    f"{cls}.{field} is set at this construction but "
                    f"never read anywhere in the analyzed modules",
                    symbol=f"{cls}.{field}"), srcline))

    # -- GL402: endpoint ↔ wrapper symmetry -----------------------------
    has_client = any((f.get("roles") or {}).get("client")
                     for f in facts_by_path.values())
    has_server = any((f.get("roles") or {}).get("server")
                     for f in facts_by_path.values())
    # a recorded client-side construction is the strongest wrapper
    # evidence of all — belt over the refs braces
    client_refs.update(client_sent)
    if has_client:
        for cls in sorted(dispatch):
            if cls in client_refs:
                continue
            path, (line, col, srcline) = sorted(dispatch[cls])[0]
            out.append((Finding(
                "GL402", path, line, col,
                f"request type {cls} is dispatched here but "
                f"MasterClient never constructs it — no client wrapper "
                f"can reach this endpoint", symbol=cls), srcline))
    if has_server:
        for cls in sorted(client_sent):
            if cls in dispatch:
                continue
            path, site = sorted(client_sent[cls])[0]
            line, col, srcline = site[0], site[1], site[4]
            out.append((Finding(
                "GL402", path, line, col,
                f"client-sent type {cls} has no servicer dispatch arm "
                f"— the wrapper can only receive 'unknown request'",
                symbol=cls), srcline))

    # -- GL403: contract literal shadowing ------------------------------
    for path, (value, line, col, srcline) in sorted(literals):
        const = contract.get(value)
        if const is None:
            continue
        out.append((Finding(
            "GL403", path, line, col,
            f"string literal {value!r} shadows the constants.py "
            f"contract {const} — import the constant",
            symbol=const), srcline))
    return out
