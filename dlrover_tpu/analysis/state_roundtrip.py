"""Pass 3: state-roundtrip analysis of state-backend participants.

Every class that participates in the crash-consistent master state
(PR 3's ``MasterStateBackend``: it defines ``export_state``/
``restore_state`` or the ``_export_extra``/``_restore_extra`` extension
hooks) makes an implicit promise: a master failover rebuilds it from the
snapshot with nothing lost. Review rounds of PRs 3–11 kept re-finding
the same two breaches by hand, so this pass proves them mechanically:

GL301  a mutable instance attribute (assigned in ``__init__`` or under
       the class's lock) that the export/restore pair never touches and
       that is not annotated ``# graftlint: ephemeral(reason)`` —
       silently reset on failover (PR 9's ``_known_chips``).
GL302  an asymmetric snapshot key: export emits a key restore never
       consumes (dead weight, or a restore that silently defaults —
       PR 3's "silently-empty worlds"), or restore reads a key export
       never emits (the default is all it will ever see).

Class families merge same-module bases (``group_class_families``), so
a base's ``export_state`` covering ``self._x`` through a subclass's
``_export_extra`` is one analysis unit. Coverage is transitive through
``self.method()`` calls reachable from the export/restore roots — a
helper the exporter delegates to covers its attributes.

Key extraction is deliberately conservative: GL302 only compares sides
whose keys are FULLY extractable (a top-level dict literal return /
``state["k"] = …`` writes on the export side; ``state["k"]`` /
``state.get("k")`` / ``state.pop("k")`` reads on the restore side). An
export built by comprehension, or a restore that iterates the whole
dict, makes that side unknown and the symmetry check stands down rather
than guess. The key literally named ``"version"`` is exempt — a format
stamp the restore side may legitimately ignore.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.analysis.findings import Finding, ephemeral_lines
from dlrover_tpu.analysis.lock_discipline import (
    _LOCK_FACTORIES,
    group_class_families,
)
from dlrover_tpu.analysis.trace_safety import _dotted_name, _import_aliases

EXPORT_METHODS = ("export_state", "_export_extra")
RESTORE_METHODS = ("restore_state", "_restore_extra")
_INIT_METHODS = {"__init__", "__post_init__"}
# container constructors whose product is mutable state worth a snapshot
_MUTABLE_CALLS = {
    "dict", "list", "set", "bytearray",
    "collections.deque", "deque",
    "collections.defaultdict", "defaultdict",
    "collections.OrderedDict", "OrderedDict",
    "collections.Counter", "Counter",
}
# a format-stamp key the restore side may legitimately never read
_VERSION_KEY = "version"


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in ("self", "cls")):
        return node.attr
    return None


class _Family:
    """One class + its same-module bases, viewed for state analysis."""

    def __init__(self, name: str, classes: List[ast.ClassDef],
                 aliases: Dict[str, str]):
        self.name = name
        self.aliases = aliases
        self.methods: Dict[str, List[ast.FunctionDef]] = {}
        for cls in classes:
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self.methods.setdefault(item.name, []).append(item)
        self.lock_attrs: Set[str] = set()
        for fns in self.methods.values():
            for fn in fns:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Assign) and isinstance(
                            node.value, ast.Call):
                        head = _dotted_name(node.value.func, aliases)
                        if head in _LOCK_FACTORIES:
                            for tgt in node.targets:
                                attr = _is_self_attr(tgt)
                                if attr:
                                    self.lock_attrs.add(attr)

    def participates(self) -> bool:
        return any(m in self.methods
                   for m in EXPORT_METHODS + RESTORE_METHODS)

    def roundtrip_reachable(self) -> Set[str]:
        """Method names reachable from the export/restore roots via
        ``self.method()`` calls (the exporter's helpers cover state)."""
        seen: Set[str] = set()
        work = [m for m in EXPORT_METHODS + RESTORE_METHODS
                if m in self.methods]
        while work:
            name = work.pop()
            if name in seen:
                continue
            seen.add(name)
            for fn in self.methods.get(name, ()):
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call) and isinstance(
                            node.func, ast.Attribute):
                        callee = _is_self_attr(node.func)
                        if callee and callee in self.methods:
                            work.append(callee)
        return seen


def _walk_own(fn: ast.FunctionDef):
    """ast.walk limited to the function's OWN body: nested defs/lambdas
    (task_entry-style helpers building NESTED payload dicts) are not
    part of the snapshot's top-level key vocabulary."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _mutable_value(expr: ast.AST, aliases: Dict[str, str]) -> bool:
    if isinstance(expr, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                         ast.ListComp, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call):
        head = _dotted_name(expr.func, aliases)
        return head in _MUTABLE_CALLS
    return False


class StateRoundtripPass:
    def run(self, relpath: str, tree: ast.Module,
            source_lines: Sequence[str]) -> List[Finding]:
        aliases = _import_aliases(tree)
        ephemeral = ephemeral_lines(source_lines)
        findings: List[Finding] = []
        classes = [n for n in tree.body if isinstance(n, ast.ClassDef)]
        for root, members in group_class_families(classes):
            family = _Family(root, members, aliases)
            if not family.participates():
                continue
            findings.extend(self._check_coverage(
                relpath, family, ephemeral))
            findings.extend(self._check_key_symmetry(relpath, family))
        return findings

    # -- GL301 -------------------------------------------------------------
    def _check_coverage(self, relpath: str, family: _Family,
                        ephemeral: Dict[int, str]) -> List[Finding]:
        reachable = family.roundtrip_reachable()

        # attribute writes, split by where they happen
        init_assigns: Dict[str, Tuple[int, int, ast.AST]] = {}
        other_writes: Dict[str, Tuple[int, int]] = {}
        locked_writes: Set[str] = set()
        write_lines: Dict[str, List[int]] = {}
        covered: Set[str] = set()

        def scan_method(name: str, fn: ast.FunctionDef) -> None:
            lock_depth = 0

            def visit(node: ast.AST) -> None:
                nonlocal lock_depth
                pushed = 0
                if isinstance(node, ast.With):
                    for item in node.items:
                        expr = item.context_expr
                        attr = _is_self_attr(expr)
                        if attr and attr in family.lock_attrs:
                            lock_depth += 1
                            pushed += 1
                for child in ast.iter_child_nodes(node):
                    visit(child)
                lock_depth -= pushed
                attr = _is_self_attr(node)
                if attr is None or attr in family.lock_attrs:
                    return
                if name in reachable:
                    covered.add(attr)
                    return
                if isinstance(node.ctx, (ast.Store, ast.Del)):
                    write_lines.setdefault(attr, []).append(node.lineno)
                    if name in _INIT_METHODS:
                        init_assigns.setdefault(
                            attr, (node.lineno, node.col_offset, node))
                    else:
                        other_writes.setdefault(
                            attr, (node.lineno, node.col_offset))
                        if lock_depth > 0:
                            locked_writes.add(attr)

            visit(fn)

        for name, fns in family.methods.items():
            for fn in fns:
                scan_method(name, fn)

        # mutability of the __init__-assigned value (per assignment
        # statement: `self.x = {}` → the Assign's value)
        mutable_init: Set[str] = set()
        for name in _INIT_METHODS:
            for fn in family.methods.get(name, ()):
                for node in ast.walk(fn):
                    # both assignment styles: `self.x = {}` AND the
                    # annotated `self.x: Dict[str, int] = {}` — the
                    # dominant style in this codebase
                    if isinstance(node, ast.Assign):
                        targets = node.targets
                        value = node.value
                    elif isinstance(node, ast.AnnAssign) and \
                            node.value is not None:
                        targets = [node.target]
                        value = node.value
                    else:
                        continue
                    if _mutable_value(value, family.aliases):
                        for tgt in targets:
                            attr = _is_self_attr(tgt)
                            if attr:
                                mutable_init.add(attr)

        candidates: Set[str] = set()
        for attr in init_assigns:
            if attr in mutable_init or attr in other_writes:
                candidates.add(attr)
        candidates |= locked_writes
        candidates -= family.lock_attrs

        findings: List[Finding] = []
        for attr in sorted(candidates):
            if attr in covered:
                continue
            # the annotation sits on the assignment line or the line
            # directly above it (79-col style: the reason rarely fits
            # beside the assignment)
            if any(line in ephemeral or line - 1 in ephemeral
                   for line in write_lines.get(attr, ())):
                continue
            line, col, _ = init_assigns.get(
                attr, other_writes.get(attr, (0, 0)) + (None,))
            findings.append(Finding(
                "GL301", relpath, line, col,
                f"'{family.name}.{attr}' is mutable state outside the "
                f"export/restore roundtrip (not exported, not restored, "
                f"not annotated `# graftlint: ephemeral(reason)`) — a "
                f"failover silently resets it",
                symbol=f"{family.name}.{attr}"))
        return findings

    # -- GL302 -------------------------------------------------------------
    def _check_key_symmetry(self, relpath: str,
                            family: _Family) -> List[Finding]:
        exported: Dict[str, Tuple[int, int]] = {}
        consumed: Dict[str, Tuple[int, int]] = {}
        export_opaque = False
        restore_opaque = False

        def state_param(fn: ast.FunctionDef) -> Optional[str]:
            params = [a.arg for a in fn.args.args if a.arg not in
                      ("self", "cls")]
            return params[0] if params else None

        for name in EXPORT_METHODS:
            for fn in family.methods.get(name, ()):
                param = state_param(fn)
                for node in _walk_own(fn):
                    if isinstance(node, ast.Return) and \
                            node.value is not None:
                        if isinstance(node.value, ast.Dict):
                            for key in node.value.keys:
                                if isinstance(key, ast.Constant) and \
                                        isinstance(key.value, str):
                                    exported.setdefault(
                                        key.value,
                                        (node.lineno, node.col_offset))
                                else:
                                    export_opaque = True  # **spread
                        elif not isinstance(node.value, ast.Constant):
                            export_opaque = True
                    elif (isinstance(node, ast.Assign)
                          and param is not None):
                        for tgt in node.targets:
                            if (isinstance(tgt, ast.Subscript)
                                    and isinstance(tgt.value, ast.Name)
                                    and tgt.value.id == param):
                                sl = tgt.slice
                                if isinstance(sl, ast.Constant) and \
                                        isinstance(sl.value, str):
                                    exported.setdefault(
                                        sl.value,
                                        (node.lineno, node.col_offset))
                                else:
                                    export_opaque = True

        for name in RESTORE_METHODS:
            for fn in family.methods.get(name, ()):
                param = state_param(fn)
                if param is None:
                    continue
                for node in _walk_own(fn):
                    # state["k"] reads
                    if (isinstance(node, ast.Subscript)
                            and isinstance(node.value, ast.Name)
                            and node.value.id == param
                            and isinstance(node.ctx, ast.Load)):
                        sl = node.slice
                        if isinstance(sl, ast.Constant) and \
                                isinstance(sl.value, str):
                            consumed.setdefault(
                                sl.value, (node.lineno, node.col_offset))
                        else:
                            restore_opaque = True
                    # state.get("k")/state.pop("k"); state.items()/
                    # .keys()/.values() or `for k in state` → opaque
                    elif isinstance(node, ast.Call) and isinstance(
                            node.func, ast.Attribute) and isinstance(
                            node.func.value, ast.Name) and \
                            node.func.value.id == param:
                        if node.func.attr in ("get", "pop") and \
                                node.args and isinstance(
                                node.args[0], ast.Constant) and \
                                isinstance(node.args[0].value, str):
                            consumed.setdefault(
                                node.args[0].value,
                                (node.lineno, node.col_offset))
                        else:
                            restore_opaque = True
                    elif isinstance(node, ast.For) and isinstance(
                            node.iter, ast.Name) and \
                            node.iter.id == param:
                        restore_opaque = True
                    # the whole dict handed to something else (a helper,
                    # json.dumps, dict(state)): its reads are invisible
                    elif isinstance(node, ast.Call):
                        for arg in list(node.args) + [
                                kw.value for kw in node.keywords]:
                            if isinstance(arg, ast.Name) and \
                                    arg.id == param:
                                callee = node.func
                                if not (isinstance(callee, ast.Attribute)
                                        and isinstance(callee.value,
                                                       ast.Name)
                                        and callee.value.id == param):
                                    restore_opaque = True

        findings: List[Finding] = []
        if exported and consumed:
            if not restore_opaque:
                for key in sorted(set(exported) - set(consumed)):
                    if key == _VERSION_KEY:
                        continue
                    line, col = exported[key]
                    findings.append(Finding(
                        "GL302", relpath, line, col,
                        f"{family.name} exports snapshot key "
                        f"'{key}' that restore never consumes",
                        symbol=f"{family.name}.{key}"))
            if not export_opaque:
                for key in sorted(set(consumed) - set(exported)):
                    line, col = consumed[key]
                    findings.append(Finding(
                        "GL302", relpath, line, col,
                        f"{family.name} restores snapshot key "
                        f"'{key}' that export never emits (the reader "
                        f"only ever sees the default)",
                        symbol=f"{family.name}.{key}"))
        return findings
