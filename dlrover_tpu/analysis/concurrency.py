"""graftrace static side: thread roster + project lock-order graph.

Two rules ride on one scan:

GL701  *cross-thread unguarded access* — per file, the pass builds the
       class's thread roster (every ``Thread(target=...)``/``Timer``/
       executor-submit site, plus RPC servicer entry points as implicit
       threads), propagates thread contexts over the internal call
       graph, and flags instance attributes written after thread start
       whose accesses span several contexts with NO lock common to all
       of them.  This is the cross-thread escalation of GL205 (which
       only counts same-class writers) and of GL201 (whose majority
       vote needs two guarded accesses before it fires).

GL702  *lock-order graph* — per file the pass EXPORTS facts: every
       acquired-while-held edge (lexical nesting, "(lock held)" helper
       entry locksets, and calls into *other* lock-owning classes while
       a lock is held), every lock definition, and module factory
       functions that return lock owners.  The pooled checker
       (:func:`check_lock_order`) then assembles the project-wide
       graph, fails on cycles, and diffs the graph both directions
       against the canonical hierarchy table in
       ``docs/fault_tolerance.md`` — same contract pattern as the
       obs-catalog drift check.

The runtime half of graftrace (``analysis/lockcheck.py``) validates
this static model under tier-1: the observed acquisition graph must be
a subset of the model here (``tools/graftrace.py --diff``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.analysis.findings import Finding
from dlrover_tpu.analysis.lock_discipline import (
    _SKIP_METHODS,
    _ClassFamily,
    _MethodScan,
    _module_lock_names,
    entry_locksets,
    group_class_families,
)
from dlrover_tpu.analysis.trace_safety import (
    _dotted_name,
    _import_aliases,
)

# Thread spawn vocabulary: constructor heads (resolved through import
# aliases) and the executor-submit method form.
_SPAWN_HEADS = {
    "threading.Thread": "thread",
    "threading.Timer": "timer",
    "concurrent.futures.ThreadPoolExecutor": "executor",
    "futures.ThreadPoolExecutor": "executor",
    "ThreadPoolExecutor": "executor",
}
# classes whose public methods run on RPC pool threads (one implicit
# thread context per endpoint): the naming convention the master's
# servicer/coord/KV classes follow
_SERVICER_SUFFIXES = ("Servicer", "Service")

_TOKEN_RE = re.compile(r"epoch|generation|round|token|stamp", re.I)


def _module_stem(relpath: str) -> str:
    """Last module-path segment, matching the runtime sanitizer's
    naming (``obs/__init__.py`` locks live on module ``...obs``)."""
    parts = relpath.split("/")
    base = parts[-1]
    if base == "__init__.py" and len(parts) > 1:
        return parts[-2]
    return base[:-3] if base.endswith(".py") else base


def _class_like(name: str) -> bool:
    """CamelCase last segment (underscore-private ``_Family`` counts)."""
    last = name.rsplit(".", 1)[-1].lstrip("_")
    return last[:1].isupper()


class _ConcScan(_MethodScan):
    """_MethodScan + spawn sites, lock acquisitions, and calls made on
    other objects while a lock is held."""

    def __init__(self, owner, method_name: str):
        super().__init__(owner, method_name)
        # (kind, target_kind, target, line)
        self.spawns: List[Tuple[str, str, str, int]] = []
        # (lock_id, line) for every `with <lock>` entry
        self.acquisitions: List[Tuple[str, int]] = []
        # calls on self.<attr>.<meth>() / factory().<meth>() and bare
        # ctor/factory calls: (held locks, receiver head, line, kind)
        self.held_calls: List[
            Tuple[Tuple[str, ...], str, int, str]] = []

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = self._lock_id(item.context_expr)
            if lock is not None:
                self.acquisitions.append((lock, item.context_expr.lineno))
                for outer in self.held:
                    if outer != lock:
                        self.order_pairs.append(
                            (outer, lock, item.context_expr, self.method))
                self.held.append(lock)
                pushed += 1
            else:
                self.visit(item.context_expr)
                if item.optional_vars is not None:
                    self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_Call(self, node: ast.Call) -> None:
        self._record_spawn(node)
        self._record_held_call(node)
        super().visit_Call(node)

    # -- spawn sites -------------------------------------------------------
    def _record_spawn(self, node: ast.Call) -> None:
        head = _dotted_name(node.func, self.owner.aliases)
        kind = _SPAWN_HEADS.get(head or "")
        target: Optional[ast.AST] = None
        if kind == "thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    target = kw.value
        elif kind == "timer":
            for kw in node.keywords:
                if kw.arg == "function":
                    target = kw.value
            if target is None and len(node.args) >= 2:
                target = node.args[1]
        elif (isinstance(node.func, ast.Attribute)
              and node.func.attr == "submit" and node.args):
            recv = node.func.value
            text = recv.attr if isinstance(recv, ast.Attribute) else (
                recv.id if isinstance(recv, ast.Name) else "")
            if any(t in text.lower() for t in ("executor", "pool")):
                kind, target = "executor", node.args[0]
        if kind is None and target is None:
            return
        tk, name = "inline", "<lambda>"
        if isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name) and target.value.id in ("self",
                                                                "cls"):
            tk, name = "method", target.attr
        elif isinstance(target, ast.Name):
            tk, name = "name", target.id
        elif target is None:
            return
        self.spawns.append((kind or "executor", tk, name, node.lineno))

    # -- cross-object calls (lock relevance decided at emission: the
    # caller's ENTRY lockset counts too, so record even when nothing is
    # lexically held here) --------------------------------------------------
    def _record_held_call(self, node: ast.Call) -> None:
        head, kind = "", "call"
        if isinstance(node.func, ast.Attribute):
            base = node.func.value
            if isinstance(base, ast.Attribute) and isinstance(
                    base.value, ast.Name) and base.value.id in ("self",
                                                                "cls"):
                head = f"self.{base.attr}"
            elif isinstance(base, ast.Call):
                inner = _dotted_name(base.func, self.owner.aliases)
                if inner:
                    head = inner
        elif isinstance(node.func, ast.Name):
            # a bare constructor / factory call binds the class into
            # this family's reach (closure fodder, not an order edge:
            # constructing a lock owner does not acquire its lock)
            resolved = _dotted_name(node.func, self.owner.aliases)
            if resolved:
                head, kind = resolved, "ctor"
        if head:
            self.held_calls.append((tuple(self.held), head,
                                    node.lineno, kind))


def _family_bindings(family: _ClassFamily) -> Dict[str, str]:
    """``self.X = ClassName(...)`` / ``self.X = factory()`` bindings:
    attr -> call head, for resolving held-call receivers."""
    out: Dict[str, str] = {}
    for _, meth in family.methods:
        for node in ast.walk(meth):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            head = _dotted_name(node.value.func, family.aliases)
            if not head:
                continue
            for tgt in node.targets:
                if (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in ("self", "cls")):
                    out.setdefault(tgt.attr, head)
    return out


def _module_factories(tree: ast.Module,
                      aliases: Dict[str, str]) -> Dict[str, str]:
    """Module functions whose body returns ``ClassName(...)`` or a
    module-level singleton bound to one — ``get_registry()`` style."""
    def _cls_name(head: Optional[str]) -> str:
        # aliases resolve imported classes to dotted paths
        # (pkg.beta.Beta): the class-ness test is on the LAST segment
        last = (head or "").rsplit(".", 1)[-1]
        return last if _class_like(last) else ""

    singleton: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            cls = _cls_name(_dotted_name(node.value.func, aliases))
            if cls:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        singleton[tgt.id] = cls
    out: Dict[str, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        # lazy singletons assign the global INSIDE the factory:
        # ``global _reg; if _reg is None: _reg = Cls(); return _reg``
        local = dict(singleton)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and isinstance(
                    sub.value, ast.Call):
                cls = _cls_name(_dotted_name(sub.value.func, aliases))
                if cls:
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            local.setdefault(tgt.id, cls)
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Return) or sub.value is None:
                continue
            val = sub.value
            if isinstance(val, ast.Call):
                cls = _cls_name(_dotted_name(val.func, aliases))
                if cls:
                    out[node.name] = cls
            elif isinstance(val, ast.Name) and val.id in local:
                out[node.name] = local[val.id]
    return out


def analyze_concurrency(
        relpath: str, tree: ast.Module,
        source_lines: Sequence[str]) -> Tuple[List[Finding], Dict]:
    """One file: GL701 findings + GL702 facts for the pooled checker."""
    aliases = _import_aliases(tree)
    stem = _module_stem(relpath)
    module_locks = _module_lock_names(tree, aliases)
    classes = [n for n in tree.body if isinstance(n, ast.ClassDef)]

    findings: List[Finding] = []
    locks: List[Dict] = [
        {"id": f"{stem}.{name}", "owner": stem, "kind": "module"}
        for name in sorted(module_locks)]
    edges: List[Dict] = []
    calls: List[Dict] = []
    binds: List[Dict] = []
    families: List[Dict] = []
    threads: List[Dict] = []
    modfuncs: List[Dict] = []

    def _qual(lock_id: str) -> str:
        return lock_id.replace("<module>.", f"{stem}.")

    def _src(line: int) -> str:
        if 1 <= line <= len(source_lines):
            return source_lines[line - 1]
        return ""

    for root, members in group_class_families(classes):
        family = _ClassFamily(root, members, aliases, relpath,
                              module_locks)
        for attr in sorted(family.lock_attrs):
            locks.append({"id": f"{family.name}.{attr}",
                          "owner": family.name, "kind": "class"})
        bindings = _family_bindings(family)
        # classes/factories this family calls into, for the runtime
        # diff's transitive closure (non-classes fall out at pool time)
        callee_names: Set[str] = set()
        scans: Dict[str, _ConcScan] = {}
        for cls, meth in family.methods:
            scan = _ConcScan(family, meth.name)
            for stmt in meth.body:
                scan.visit(stmt)
            scans[f"{cls.name}.{meth.name}"] = scan
        entries = entry_locksets(scans)

        # -- GL702 facts ---------------------------------------------------
        for key, scan in scans.items():
            meth_name = key.split(".", 1)[1]
            for outer, inner, node, _ in scan.order_pairs:
                edges.append({"outer": _qual(outer),
                              "inner": _qual(inner),
                              "line": node.lineno,
                              "srcline": _src(node.lineno),
                              "symbol": key})
            entry = entries.get(meth_name, frozenset())
            for lock, line in scan.acquisitions:
                for held in entry:
                    if held != lock:
                        edges.append({"outer": _qual(held),
                                      "inner": _qual(lock),
                                      "line": line,
                                      "srcline": _src(line),
                                      "symbol": key})
            for held, head, line, kind in scan.held_calls:
                recv = head
                if head.startswith("self."):
                    recv = bindings.get(head[5:], "")
                if not recv:
                    continue
                recv = recv.rsplit(".", 1)[-1]
                callee_names.add(recv)
                # a helper whose every caller holds a lock ("(lock
                # held)" entry lockset) makes its calls lock-held too.
                # Ctor sites stay facts as well: constructing a lock
                # owner acquires nothing, but a bare-name call can be
                # a module FUNCTION that takes a module lock — the
                # pool tells those apart by the kind tag.
                for h in sorted(set(held) | set(entry)):
                    calls.append({"held": _qual(h), "head": recv,
                                  "line": line,
                                  "srcline": _src(line),
                                  "symbol": key, "kind": kind})
            for kind, tk, name, line in scan.spawns:
                threads.append({"owner": family.name, "kind": kind,
                                "target": name, "target_kind": tk,
                                "line": line, "symbol": key})

        for head in bindings.values():
            callee_names.add(head.rsplit(".", 1)[-1])
        if callee_names:
            binds.append({"owner": family.name,
                          "callees": sorted(callee_names)})
        # membership + external bases: the runtime sanitizer names a
        # lock after the INSTANCE class, which may be a subclass (even
        # cross-module) of the family that defines the attribute
        member_names = [c.name for c in family.classes]
        base_names: Set[str] = set()
        for c in family.classes:
            for b in c.bases:
                last = (_dotted_name(b, aliases) or "").rsplit(
                    ".", 1)[-1]
                if last and last not in member_names \
                        and _class_like(last):
                    base_names.add(last)
        families.append({"name": family.name, "members": member_names,
                         "bases": sorted(base_names)})

        findings.extend(_check_family_threads(family, scans, entries,
                                              relpath, source_lines))

    # module-level functions: spawns, plus lock facts — which module
    # locks each function acquires and what it calls while one is
    # held.  Class code reaching ``obs.get_registry()`` under its own
    # lock picks up ``metrics._default_lock``; the pool and the
    # runtime closure need these to model that.
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            owner = _ModuleScanOwner(aliases, module_locks)
            scan = _ConcScan(owner, node.name)
            for stmt in node.body:
                scan.visit(stmt)
            for kind, tk, name, line in scan.spawns:
                threads.append({"owner": f"<{stem}>", "kind": kind,
                                "target": name, "target_kind": tk,
                                "line": line, "symbol": node.name})
            for outer, inner, onode, _ in scan.order_pairs:
                edges.append({"outer": _qual(outer),
                              "inner": _qual(inner),
                              "line": onode.lineno,
                              "srcline": _src(onode.lineno),
                              "symbol": node.name})
            fn_callees: Set[str] = set()
            fn_calls: List[Dict] = []
            for held, head, line, _kind in scan.held_calls:
                callee = head.rsplit(".", 1)[-1]
                fn_callees.add(callee)
                for h in held:
                    fn_calls.append({"held": _qual(h), "head": callee,
                                     "line": line,
                                     "srcline": _src(line),
                                     "symbol": node.name})
            acquired = sorted({_qual(lock)
                               for lock, _ in scan.acquisitions})
            if acquired or fn_callees:
                modfuncs.append({"name": node.name, "locks": acquired,
                                 "callees": sorted(fn_callees),
                                 "calls": fn_calls})

    facts: Dict = {}
    if locks or edges or calls or binds or families or threads \
            or modfuncs:
        facts = {"locks": locks, "edges": edges, "calls": calls,
                 "binds": binds, "families": families,
                 "threads": threads, "modfuncs": modfuncs,
                 "factories": _module_factories(tree, aliases)}
    return findings, facts


class _ModuleScanOwner:
    """Module-function duck-type owner for _ConcScan (mirrors the
    lock-discipline pass's _ModuleOwner, kept separate to avoid
    importing a private name)."""

    def __init__(self, aliases: Dict[str, str], module_locks: Set[str]):
        self.aliases = aliases
        self.module_locks = module_locks
        self.lock_attrs: Set[str] = set()
        self.method_names: Set[str] = set()

    def lock_id(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"<module>.{expr.id}"
        return None


# -- GL701: cross-thread unguarded access -----------------------------------

_VOUCHED_RE = re.compile(
    r"#\s*graftlint:\s*disable=[^#]*GL(?:201|205|701)")


def _check_family_threads(family: _ClassFamily,
                          scans: Dict[str, _ConcScan],
                          entries: Dict[str, frozenset],
                          relpath: str,
                          source_lines: Sequence[str]) -> List[Finding]:
    servicer = any(c.name.endswith(_SERVICER_SUFFIXES)
                   for c in family.classes)
    thread_entries: Set[str] = set()
    spawner_methods: Dict[str, int] = {}   # method -> first spawn line
    for key, scan in scans.items():
        m = key.split(".", 1)[1]
        for _, tk, name, line in scan.spawns:
            if tk == "method":
                thread_entries.add(name)
            else:
                spawner_methods.setdefault(m, line)
                spawner_methods[m] = min(spawner_methods[m], line)
    if not thread_entries and not spawner_methods and not servicer:
        return []

    # base contexts, propagated over the internal call graph.  The
    # constructor gets its own "init" context: everything it (and the
    # helpers only it calls) writes is published before Thread.start()
    # and therefore happens-before every spawned thread's first read —
    # unless construction itself spawns, which voids the ordering.
    init_spawns = any(key.split(".", 1)[1] in _SKIP_METHODS
                      and scan.spawns for key, scan in scans.items())
    methods = {k.split(".", 1)[1] for k in scans}
    ctx: Dict[str, Set[str]] = {}
    for m in methods:
        s: Set[str] = set()
        if m in _SKIP_METHODS:
            s.add("init")
        elif not m.startswith("_"):
            s.add(f"rpc:{m}" if servicer else "main")
        if m in thread_entries:
            s.add(f"thread:{m}")
        ctx[m] = s
    call_edges = [(key.split(".", 1)[1], cs.callee)
                  for key, scan in scans.items() for cs in scan.calls]
    changed = True
    while changed:
        changed = False
        for caller, callee in call_edges:
            if callee in ctx and not ctx[caller] <= ctx[callee]:
                ctx[callee] |= ctx[caller]
                changed = True
    for m in methods:
        if not ctx[m]:
            ctx[m] = {"main"}    # externally-driven helper: assume main

    # effective locksets + contexts per access
    by_attr: Dict[str, List[Tuple[Set[str], Set[str], bool, bool,
                                  int, int, str]]] = {}
    for key, scan in scans.items():
        m = key.split(".", 1)[1]
        if m in _SKIP_METHODS:
            continue
        entry = entries.get(m, frozenset())
        spawn_line = spawner_methods.get(m)
        for acc in scan.accesses:
            if acc.attr not in family.instance_attrs:
                continue
            # a per-line lock-discipline suppression (the deliberate
            # lock-free fast path idiom) vouches the access: it does not
            # poison the attribute's common lockset
            if 1 <= acc.line <= len(source_lines) and _VOUCHED_RE.search(
                    source_lines[acc.line - 1]):
                continue
            held = set(acc.held)
            if not acc.in_nested_def:
                held |= entry
            if acc.in_nested_def and m in spawner_methods:
                contexts = {f"thread:{m}.<inline>"}
            else:
                contexts = set(ctx[m])
            pre_spawn = (acc.is_write and not acc.in_nested_def
                         and spawn_line is not None
                         and acc.line <= spawn_line)
            if contexts and contexts <= {"init"} and not init_spawns:
                pre_spawn = True    # init-only helper: happens-before
            by_attr.setdefault(acc.attr, []).append(
                (held, contexts, acc.is_write, pre_spawn,
                 acc.line, acc.col, key))

    findings: List[Finding] = []
    for attr, accs in sorted(by_attr.items()):
        live = [a for a in accs if not a[3]]      # drop pre-spawn pubs
        writes = [a for a in live if a[2]]
        if not writes:
            continue
        allctx: Set[str] = set()
        for a in live:
            allctx |= a[1]
        allctx.discard("init")     # construction is not a live context
        if len(allctx) < 2 or not any(
                c.startswith(("thread:", "rpc:")) for c in allctx):
            continue
        common = None
        for a in live:
            common = set(a[0]) if common is None else (common & a[0])
        if common:
            continue
        # GL205 already covers the all-lockless multi-writer shape in a
        # lock-owning class — don't double-report
        writer_methods = {a[6] for a in writes}
        if (family.lock_attrs and len(writer_methods) >= 2
                and not any(a[0] for a in accs)):
            continue
        ctx_desc = ", ".join(sorted(allctx))
        for held, _, _, _, line, col, key in sorted(
                writes, key=lambda a: (a[4], a[5])):
            findings.append(Finding(
                "GL701", relpath, line, col,
                f"'{family.name}.{attr}' is accessed from several "
                f"thread contexts ({ctx_desc}) with no lock common to "
                f"all accesses", symbol=key))
    return findings


class ConcurrencyPass:
    """Per-file GL701 wrapper (fixture/analyze_file entry point)."""

    def run(self, relpath: str, tree: ast.Module,
            source_lines: Sequence[str]) -> List[Finding]:
        findings, _ = analyze_concurrency(relpath, tree, source_lines)
        return findings


# -- GL702: the pooled project lock-order graph -----------------------------

_DOC_HEADING_RE = re.compile(
    r"lock[- ](?:order|hierarchy)", re.I)
_DOC_ROW_RE = re.compile(
    r"^\|\s*`([^`]+)`\s*\|\s*`([^`]+)`\s*\|")


def parse_lock_table(doc_text: str) -> Dict[Tuple[str, str], int]:
    """(outer, inner) -> 1-based doc line, from the first markdown
    table under a heading mentioning the lock order/hierarchy."""
    rows: Dict[Tuple[str, str], int] = {}
    in_section = False
    for i, line in enumerate(doc_text.splitlines(), start=1):
        if line.startswith("#"):
            in_section = bool(_DOC_HEADING_RE.search(line))
            continue
        if not in_section:
            continue
        m = _DOC_ROW_RE.match(line.strip())
        if m:
            outer, inner = m.group(1).strip(), m.group(2).strip()
            if outer.lower() in ("outer", "held lock"):
                continue          # header row
            rows.setdefault((outer, inner), i)
    return rows


def build_lock_model(facts_by_path: Dict[str, Dict]) -> Dict:
    """Pool the per-file concurrency facts into the project model the
    doc check, the cycle check and `tools/graftrace.py --diff` share."""
    locks: Dict[str, Dict] = {}
    class_locks: Dict[str, List[str]] = {}
    factories: Dict[str, str] = {}
    func_locks: Dict[str, Set[str]] = {}
    func_callees: Dict[str, Set[str]] = {}
    mf_calls: List[Tuple[Dict, str]] = []
    for path, facts in sorted(facts_by_path.items()):
        conc = (facts or {}).get("conc") or {}
        for entry in conc.get("locks", ()):
            locks.setdefault(entry["id"], dict(entry, path=path))
            if entry.get("kind") == "class":
                class_locks.setdefault(entry["owner"], []).append(
                    entry["id"])
        factories.update(conc.get("factories") or {})
        for mf in conc.get("modfuncs", ()):
            func_locks.setdefault(mf["name"], set()).update(
                mf["locks"])
            func_callees.setdefault(mf["name"], set()).update(
                mf["callees"])
            mf_calls.extend((dict(c), path)
                            for c in mf.get("calls", ()))

    # transitive module-lock reach per function: ``f`` calling ``g``
    # calling ``h`` which takes a module lock means calling ``f`` can
    # take it.  Keyed by bare name like ``factories`` — collisions
    # across modules over-approximate, which is the safe direction.
    func_reach: Dict[str, Set[str]] = {
        name: set(func_locks.get(name, ()))
        for name in set(func_locks) | set(func_callees)}
    changed = True
    while changed:
        changed = False
        for name, callees in func_callees.items():
            for callee in callees:
                extra = func_reach.get(callee, set()) - func_reach[name]
                if extra:
                    func_reach[name] |= extra
                    changed = True

    # labeled edges: (outer, inner-label) -> first site; inner-label is
    # an exact lock id, or "Cls.*" for a call into another lock owner
    labeled: Dict[Tuple[str, str], Dict] = {}
    expanded: Dict[Tuple[str, str], Tuple[str, str]] = {}
    threads: List[Dict] = []
    for path, facts in sorted(facts_by_path.items()):
        conc = (facts or {}).get("conc") or {}
        for e in conc.get("edges", ()):
            lab = (e["outer"], e["inner"])
            labeled.setdefault(lab, dict(e, path=path))
            expanded.setdefault((e["outer"], e["inner"]), lab)
        for c in conc.get("calls", ()):
            cls = c["head"]
            if cls not in class_locks:
                cls = factories.get(cls, "")
            if (cls in class_locks and c.get("kind") != "ctor"
                    and not c["held"].startswith(f"{cls}.")):
                lab = (c["held"], f"{cls}.*")
                labeled.setdefault(lab, dict(c, path=path))
                for inner in class_locks[cls]:
                    expanded.setdefault((c["held"], inner), lab)
            # a call into a module function that itself takes a
            # module lock is an order edge too (DIRECT locks only:
            # transitive reach is runtime-closure material, not a
            # doc-table row)
            for inner in sorted(func_locks.get(c["head"], ())):
                if inner != c["held"]:
                    lab = (c["held"], inner)
                    labeled.setdefault(lab, dict(c, path=path))
                    expanded.setdefault(lab, lab)
        threads.extend(dict(t, path=path)
                       for t in conc.get("threads", ()))
    # module functions calling other functions with a module lock held
    # (``_install_defaults`` holding obs._defaults_lock while calling
    # spans.add_span_sink, which takes spans._sink_lock)
    for c, path in mf_calls:
        for inner in sorted(func_locks.get(c["head"], ())):
            if inner != c["held"]:
                lab = (c["held"], inner)
                labeled.setdefault(lab, dict(c, path=path))
                expanded.setdefault(lab, lab)

    # class-call graph: which classes each family reaches (ctor calls,
    # factory calls, bound-attr receivers), for the runtime closure
    class_calls: Dict[str, Set[str]] = {}
    class_callees: Dict[str, Set[str]] = {}
    member_family: Dict[str, str] = {}
    family_bases: Dict[str, Set[str]] = {}
    for path, facts in sorted(facts_by_path.items()):
        conc = (facts or {}).get("conc") or {}
        for b in conc.get("binds", ()):
            class_callees.setdefault(b["owner"], set()).update(
                b["callees"])
            tgt = class_calls.setdefault(b["owner"], set())
            for name in b["callees"]:
                cls = name if _class_like(name) else factories.get(
                    name, "")
                if cls and cls != b["owner"]:
                    tgt.add(cls)
        for f in conc.get("families", ()):
            for m in f["members"]:
                member_family.setdefault(m, f["name"])
            family_bases.setdefault(f["name"], set()).update(
                f.get("bases", ()))

    # runtime lock ids per CONCRETE class: a subclass instance names
    # the inherited lock after itself (``_ShardInner._lock``), so give
    # every member its ancestors' lock attrs under its own name
    fam_attrs: Dict[str, Set[str]] = {}
    for fam, ids in class_locks.items():
        fam_attrs[fam] = {i.split(".", 1)[1] for i in ids}

    def _all_attrs(fam: str, seen: Set[str]) -> Set[str]:
        if fam in seen:
            return set()
        seen.add(fam)
        attrs = set(fam_attrs.get(fam, ()))
        for base in family_bases.get(fam, ()):
            attrs |= _all_attrs(member_family.get(base, base), seen)
        return attrs

    runtime_class_locks: Dict[str, List[str]] = {}
    for member, fam in member_family.items():
        attrs = _all_attrs(fam, set())
        if attrs:
            runtime_class_locks[member] = sorted(
                f"{member}.{a}" for a in attrs)

    return {"locks": locks, "edges": labeled, "expanded": expanded,
            "threads": threads, "class_locks": class_locks,
            "class_calls": {k: sorted(v)
                            for k, v in class_calls.items()},
            "class_callees": {k: sorted(v)
                              for k, v in class_callees.items()},
            "func_reach_locks": {k: sorted(v)
                                 for k, v in func_reach.items()},
            "modfunc_calls": [c for c, _ in mf_calls],
            "member_family": member_family,
            "runtime_class_locks": runtime_class_locks}


def runtime_pairs(model: Dict) -> Set[Tuple[str, str]]:
    """Over-approximate acquired-while-held pairs for the runtime diff.

    ``model["expanded"]`` is one-hop: ``A.lock -> B.*`` says B's locks
    can be taken while A's is held, but code running under B's methods
    may reach C and take C's lock with A's STILL held — the runtime
    sanitizer reports that as ``A.lock -> C.lock``.  Close every edge's
    inner endpoint over the class-call graph so such multi-hop
    observations don't read as model gaps.  Cycle detection and the
    doc-table diff stay on the un-closured graph: the closure is too
    coarse for findings (it would manufacture order edges from mere
    reachability)."""
    calls = model.get("class_calls", {})
    member_family = model.get("member_family", {})
    rt_locks = model.get("runtime_class_locks",
                         model.get("class_locks", {}))
    memo: Dict[str, Set[str]] = {}

    def reach(cls: str) -> Set[str]:
        if cls in memo:
            return memo[cls]
        seen: Set[str] = set()
        stack = [cls]
        while stack:
            cur = stack.pop()
            if cur in seen:
                continue
            seen.add(cur)
            # call edges are keyed by FAMILY name; callees are
            # concrete class names
            stack.extend(calls.get(member_family.get(cur, cur), ()))
        memo[cls] = seen
        return seen

    pairs: Set[Tuple[str, str]] = set(model["expanded"])
    starts: Dict[str, Set[str]] = {}
    for outer, label in model["edges"]:
        base = (label[:-2] if label.endswith(".*")
                else label.rsplit(".", 1)[0])
        starts.setdefault(outer, set()).add(base)
    for lock_id, entry in model["locks"].items():
        # code holding a class's lock IS that class's code: anything
        # the owner reaches (incl. local-var receivers the per-site
        # resolution can't see) may be acquired while it is held
        if entry.get("kind") == "class":
            starts.setdefault(lock_id, set()).add(entry["owner"])
    callee_names = model.get("class_callees", {})
    func_reach = model.get("func_reach_locks", {})
    for outer, bases in starts.items():
        for base in bases:
            for cls in reach(base):
                for lock_id in rt_locks.get(cls, ()):
                    if lock_id != outer:
                        pairs.add((outer, lock_id))
                # reached code may call module functions that take
                # module-level locks (``obs.get_registry()`` on the
                # snapshot path): their transitive reach counts too
                fam = member_family.get(cls, cls)
                for name in callee_names.get(fam, ()):
                    for lock_id in func_reach.get(name, ()):
                        if lock_id != outer:
                            pairs.add((outer, lock_id))
    # module-function call sites with a module lock held close over
    # the callee's full transitive reach (the labeled edge is direct)
    for c in model.get("modfunc_calls", ()):
        for lock_id in func_reach.get(c["head"], ()):
            if lock_id != c["held"]:
                pairs.add((c["held"], lock_id))
    return pairs


def find_cycles(edge_pairs) -> List[List[str]]:
    """Elementary cycles (shortest-first DFS, deduped by node set)."""
    graph: Dict[str, List[str]] = {}
    for a, b in edge_pairs:
        graph.setdefault(a, []).append(b)
    seen_sets: Set[frozenset] = set()
    cycles: List[List[str]] = []

    def dfs(start: str, node: str, path: List[str],
            visited: Set[str]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) > 1:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(list(path))
            elif nxt not in visited and nxt > start:
                # canonical start = smallest node: each cycle found once
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    # self-loops can't happen (emitters skip outer == inner)
    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def check_lock_order(
        facts_by_path: Dict[str, Dict],
        doc_rel: Optional[str] = None,
        doc_text: Optional[str] = None,
) -> List[Tuple[Finding, str]]:
    model = build_lock_model(facts_by_path)
    labeled: Dict[Tuple[str, str], Dict] = model["edges"]
    out: List[Tuple[Finding, str]] = []

    for cycle in find_cycles(model["expanded"]):
        # anchor on the first labeled site along the cycle
        sites = []
        ring = cycle + cycle[:1]
        for a, b in zip(ring, ring[1:]):
            lab = model["expanded"].get((a, b))
            if lab and lab in labeled:
                sites.append(labeled[lab])
        sites.sort(key=lambda s: (s["path"], s["line"]))
        site = sites[0] if sites else {"path": "<unknown>", "line": 1,
                                       "srcline": "", "symbol": ""}
        chain = " -> ".join(cycle + cycle[:1])
        out.append((Finding(
            "GL702", site["path"], site["line"], 0,
            f"lock-order cycle: {chain} (deadlock when the threads "
            f"interleave); break the cycle or merge the critical "
            f"sections", symbol=site.get("symbol", "")),
            site.get("srcline", "")))

    if doc_text is not None and doc_rel is not None:
        rows = parse_lock_table(doc_text)
        doc_lines = doc_text.splitlines()
        if not rows and labeled:
            out.append((Finding(
                "GL702", doc_rel, 1, 0,
                f"{doc_rel} has no lock-order table but the package "
                f"has {len(labeled)} acquired-while-held edge(s); add "
                f"the canonical hierarchy section "
                f"(tools/graftrace.py --markdown prints the rows)",
                symbol=""), ""))
        else:
            for lab, site in sorted(labeled.items(),
                                    key=lambda kv: (kv[1]["path"],
                                                    kv[1]["line"])):
                if lab not in rows:
                    out.append((Finding(
                        "GL702", site["path"], site["line"], 0,
                        f"acquired-while-held edge {lab[0]} -> "
                        f"{lab[1]} is missing from the lock-order "
                        f"table in {doc_rel}", symbol=site.get(
                            "symbol", "")), site.get("srcline", "")))
            for (outer, inner), line in sorted(rows.items(),
                                               key=lambda kv: kv[1]):
                if (outer, inner) not in labeled:
                    src = doc_lines[line - 1] if line <= len(
                        doc_lines) else ""
                    out.append((Finding(
                        "GL702", doc_rel, line, 0,
                        f"documented lock-order edge {outer} -> "
                        f"{inner} matches no acquired-while-held site "
                        f"in the code", symbol=""), src))
    return out
