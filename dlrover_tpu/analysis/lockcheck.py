"""graftrace runtime lock sanitizer — the dynamic half of GL702/GL501.

Env-gated (``DLROVER_TPU_LOCKCHECK=1`` via the tests/conftest.py
session fixture, or explicitly through ``tools/graftrace.py --run``):
``install()`` replaces ``threading.Lock``/``threading.RLock`` with a
tracing proxy for locks *created by this package's code* (creation-
frame filename filter), plus thin wrappers over the blocking vocab
(sleep / fsync / replace / open / connect) that record any blocking
call made while a traced lock is held.

What it records, per process:

- the **observed acquisition-order graph**: for every successful
  acquire, one edge from each lock the thread already holds to the new
  one (first sample site kept per edge);
- **hold times** per lock (count / max / total) — the "longest hold"
  table in the report;
- **blocking-under-lock events**, classified *hot* when the held lock
  belongs to a gradient-path owner (the same
  ``lock_discipline._HOT_CLASS_NAMES`` / dcn_sync roster GL5xx uses).

``report()`` resolves lock names lazily by scanning live objects for
the attribute holding each proxy (``Cls.attr``, matching the static
GL702 lock ids; a ``threading.Condition`` is traced through its inner
lock and resolves to the condition's own attribute name), detects
cycles with the same ``find_cycles`` the static pass uses, and returns
a JSON-able dict.  ``tools/graftrace.py`` diffs the observed graph
against the static model both directions: an observed edge the static
model lacks is a *model gap* (fail); a modeled edge never observed is
a *coverage gap* (report only).

Caveats (by design): locks created before ``install()`` — e.g. module
import-time singletons — are invisible; locks never resolved to an
attribute show as ``file.py:line`` and are excluded from the static
diff (the static model has no name for them either).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

ENV_FLAG = "DLROVER_TPU_LOCKCHECK"
ENV_OUT = "DLROVER_TPU_LOCKCHECK_OUT"
DEFAULT_OUT = "/tmp/graftrace_lockcheck.json"

# mirror the static hot roster (lock_discipline) without importing the
# analyzer into the runtime path
_HOT_OWNERS = {"KVStoreService", "MutationLog", "SliceGradSync",
               "StepTimeline"}
_HOT_FILE_SUFFIXES = ("parallel/dcn_sync.py",)

_perf = time.perf_counter


class _Held:
    __slots__ = ("proxy", "t0", "depth")

    def __init__(self, proxy: "_TracedLock", t0: float):
        self.proxy = proxy
        self.t0 = t0
        self.depth = 1


class _State:
    """One sanitizer session (module-global singleton while installed)."""

    def __init__(self) -> None:
        # the sanitizer's own lock must be a REAL lock (allocated from
        # the saved original), or tracing would recurse into itself
        self.mutex = _ORIG["lock"]()
        self.tls = threading.local()
        self.locks: List["_TracedLock"] = []
        # (id(outer), id(inner)) -> first sample {site, thread}
        self.edges: Dict[Tuple[int, int], Dict] = {}
        self.blocking: List[Dict] = []

    def stack(self) -> List[_Held]:
        st = getattr(self.tls, "stack", None)
        if st is None:
            st = []
            self.tls.stack = st
        return st


_ORIG: Dict[str, Any] = {}
_state: Optional[_State] = None
_trace_roots: Tuple[str, ...] = ()


def _caller_site(depth: int = 2) -> str:
    """First frame outside this module (``with lock:`` adds an
    ``__enter__`` hop, so a fixed depth under-shoots)."""
    try:
        frame = sys._getframe(depth)
        while frame is not None and \
                frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:
            return "<unknown>"
        return f"{frame.f_code.co_filename}:{frame.f_lineno}"
    except (ValueError, AttributeError):
        return "<unknown>"


def _is_traced_frame(depth: int = 2) -> bool:
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return False
    filename = frame.f_code.co_filename
    return filename.startswith(_trace_roots)


class _TracedLock:
    """Proxy over a real Lock/RLock recording order/hold/blocking facts.

    Implements the private Condition protocol (``_release_save`` /
    ``_acquire_restore`` / ``_is_owned``) with stack bookkeeping so a
    ``Condition.wait`` — which fully releases the lock — does not leave
    phantom held entries behind."""

    def __init__(self, real, site: str):
        self._real = real
        self._site = site
        self._name: Optional[str] = None
        self._acquisitions = 0
        self._max_hold = 0.0
        self._total_hold = 0.0

    # -- bookkeeping -----------------------------------------------------
    def _push(self) -> None:
        st = _state.stack()
        for held in st:
            if held.proxy is self:
                held.depth += 1          # reentrant RLock acquire
                return
        site = _caller_site(3)
        thread = threading.current_thread().name
        with _state.mutex:
            self._acquisitions += 1
            for held in st:
                _state.edges.setdefault(
                    (id(held.proxy), id(self)),
                    {"site": site, "thread": thread})
        st.append(_Held(self, _perf()))

    def _pop(self) -> None:
        st = _state.stack()
        for i in range(len(st) - 1, -1, -1):
            held = st[i]
            if held.proxy is self:
                if held.depth > 1:
                    held.depth -= 1
                    return
                del st[i]
                dur = _perf() - held.t0
                with _state.mutex:
                    self._total_hold += dur
                    if dur > self._max_hold:
                        self._max_hold = dur
                return

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        ok = self._real.acquire(blocking, timeout)
        if ok:
            self._push()
        return ok

    def release(self) -> None:
        self._pop()
        self._real.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self) -> bool:
        return self._real.locked()

    # -- Condition protocol ----------------------------------------------
    def _release_save(self):
        # wait() drops the lock wholesale, whatever the RLock depth
        st = _state.stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i].proxy is self:
                held = st[i]
                del st[i]
                dur = _perf() - held.t0
                with _state.mutex:
                    self._total_hold += dur
                    if dur > self._max_hold:
                        self._max_hold = dur
                break
        if hasattr(self._real, "_release_save"):
            return self._real._release_save()
        self._real.release()
        return None

    def _acquire_restore(self, state) -> None:
        if hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(state)
        else:
            self._real.acquire()
        self._push()

    def _is_owned(self) -> bool:
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        # plain Lock heuristic (Condition over Lock): owned if held in
        # this thread's traced stack
        return any(h.proxy is self for h in _state.stack())

    def __repr__(self) -> str:
        return f"<_TracedLock {self._name or self._site} {self._real!r}>"


def _make_factory(kind: str):
    real_factory = _ORIG[kind]

    def factory(*args, **kwargs):
        real = real_factory(*args, **kwargs)
        if _state is None or not _is_traced_frame(2):
            return real
        proxy = _TracedLock(real, _caller_site(2))
        with _state.mutex:
            _state.locks.append(proxy)
        return proxy

    return factory


def _make_blocking_wrapper(name: str, real):
    def wrapper(*args, **kwargs):
        st = getattr(_state.tls, "stack", None) if _state else None
        if not st:
            return real(*args, **kwargs)
        t0 = _perf()
        try:
            return real(*args, **kwargs)
        finally:
            dur = _perf() - t0
            event = {
                "func": name,
                "duration_s": round(dur, 6),
                "held": [id(h.proxy) for h in st],
                "site": _caller_site(2),
                "thread": threading.current_thread().name,
            }
            with _state.mutex:
                _state.blocking.append(event)

    return wrapper


_BLOCKING_PATCHES = (
    ("time", "sleep"),
    ("os", "fsync"),
    ("os", "replace"),
    ("builtins", "open"),
    ("socket", "create_connection"),
)


def install(package_dir: Optional[str] = None,
            extra_paths: Tuple[str, ...] = ()) -> None:
    """Start tracing.  ``package_dir`` defaults to the dlrover_tpu
    package; only locks created from files under it (or
    ``extra_paths``) are proxied."""
    global _state, _trace_roots
    if _state is not None:
        return
    if package_dir is None:
        import dlrover_tpu
        package_dir = os.path.dirname(os.path.abspath(
            dlrover_tpu.__file__))
    _trace_roots = tuple(os.path.abspath(p)
                         for p in (package_dir,) + tuple(extra_paths))
    _ORIG["lock"] = threading.Lock
    _ORIG["rlock"] = threading.RLock
    _state = _State()
    threading.Lock = _make_factory("lock")
    threading.RLock = _make_factory("rlock")
    import builtins
    import socket
    modules = {"time": time, "os": os, "builtins": builtins,
               "socket": socket}
    for mod_name, attr in _BLOCKING_PATCHES:
        mod = modules[mod_name]
        real = getattr(mod, attr)
        _ORIG[f"{mod_name}.{attr}"] = real
        setattr(mod, attr, _make_blocking_wrapper(
            f"{mod_name}.{attr}", real))


def uninstall() -> None:
    """Stop tracing and restore every patched callable (the collected
    state survives for a final ``report()``)."""
    global _state, _trace_roots
    if _state is None:
        return
    threading.Lock = _ORIG["lock"]
    threading.RLock = _ORIG["rlock"]
    import builtins
    import socket
    modules = {"time": time, "os": os, "builtins": builtins,
               "socket": socket}
    for mod_name, attr in _BLOCKING_PATCHES:
        setattr(modules[mod_name], attr, _ORIG[f"{mod_name}.{attr}"])
    _trace_roots = ()
    # keep _state for report-after-uninstall; installed-ness is tracked
    # by the patched factories, which are gone now


def installed() -> bool:
    return _state is not None and threading.Lock is not _ORIG.get("lock")


def _resolve_names() -> None:
    """Best-effort lock naming: find the attribute each proxy (or the
    Condition wrapping it) lives under, yielding the static model's
    ``Cls.attr`` / ``module.attr`` ids."""
    import gc

    by_id = {id(p): p for p in _state.locks if p._name is None}
    if not by_id:
        return
    for obj in gc.get_objects():
        if isinstance(obj, (_TracedLock, dict, list, tuple)):
            continue
        try:
            d = getattr(obj, "__dict__", None)
        except Exception:  # noqa: BLE001 — exotic descriptors
            continue
        if not isinstance(d, dict):
            continue
        if isinstance(obj, type(sys)):                 # a module
            try:
                owner = obj.__name__.rsplit(".", 1)[-1]
            except Exception:  # noqa: BLE001 — lazy-loader module
                continue       # shims (TF/Keras) raise on __name__
        else:
            owner = type(obj).__name__
        for attr, val in list(d.items()):
            target = None
            if isinstance(val, _TracedLock):
                target = val
            elif isinstance(val, threading.Condition) and isinstance(
                    getattr(val, "_lock", None), _TracedLock):
                target = val._lock
            if target is not None and id(target) in by_id \
                    and target._name is None:
                target._name = f"{owner}.{attr}"
        if not any(p._name is None for p in by_id.values()):
            break


def _fallback_name(proxy: "_TracedLock") -> str:
    site = proxy._site
    return os.path.basename(site.rsplit(":", 1)[0]) + ":" + \
        site.rsplit(":", 1)[-1]


def _lock_name(proxy: "_TracedLock") -> str:
    return proxy._name or _fallback_name(proxy)


def _is_hot(proxy: "_TracedLock") -> bool:
    name = proxy._name or ""
    owner = name.split(".", 1)[0] if "." in name else ""
    if owner in _HOT_OWNERS:
        return True
    created = proxy._site.rsplit(":", 1)[0]
    return created.endswith(_HOT_FILE_SUFFIXES)


def report() -> Dict:
    """Resolve names, aggregate instance-level facts to name level, and
    return the flight-style dict ``tools/graftrace.py`` consumes."""
    from dlrover_tpu.analysis.concurrency import find_cycles

    if _state is None:
        return {"enabled": False, "locks": [], "edges": [],
                "cycles": [], "hot_blocking": [], "blocking": []}
    with _state.mutex:
        locks = list(_state.locks)
        edges = dict(_state.edges)
        blocking = list(_state.blocking)
    _resolve_names()
    by_id = {id(p): p for p in locks}

    lock_rows = []
    for p in sorted(locks, key=_lock_name):
        lock_rows.append({
            "name": _lock_name(p), "resolved": p._name is not None,
            "site": p._site, "hot": _is_hot(p),
            "acquisitions": p._acquisitions,
            "max_hold_s": round(p._max_hold, 6),
            "total_hold_s": round(p._total_hold, 6),
        })

    # aggregate by name: several instances of one class share an id
    named_edges: Dict[Tuple[str, str], Dict] = {}
    for (outer_id, inner_id), sample in edges.items():
        outer = by_id.get(outer_id)
        inner = by_id.get(inner_id)
        if outer is None or inner is None:
            continue
        key = (_lock_name(outer), _lock_name(inner))
        if key[0] == key[1]:
            continue            # same-name reentrancy across instances
        entry = named_edges.setdefault(key, dict(
            sample, outer=key[0], inner=key[1],
            resolved=(outer._name is not None
                      and inner._name is not None)))
        entry["resolved"] = entry["resolved"] or (
            outer._name is not None and inner._name is not None)
    edge_rows = [named_edges[k] for k in sorted(named_edges)]

    cycles = find_cycles(list(named_edges))

    blocking_rows = []
    hot_rows = []
    for ev in blocking:
        held = [by_id[h] for h in ev["held"] if h in by_id]
        row = dict(ev, held=[_lock_name(p) for p in held])
        blocking_rows.append(row)
        hot_held = [_lock_name(p) for p in held if _is_hot(p)]
        if hot_held:
            hot_rows.append(dict(row, hot_held=hot_held))

    return {
        "enabled": True,
        "locks": lock_rows,
        "edges": edge_rows,
        "cycles": cycles,
        "blocking": blocking_rows,
        "hot_blocking": hot_rows,
    }


def observed_static_diff(rep: Dict, static_pairs,
                         coverage_pairs=None) -> Dict:
    """Two-way diff: observed edges with both endpoints resolved that
    the static model lacks (model gap → the static pass is blind to a
    real nesting: FAIL), and static edges never observed (coverage gap
    → report only).

    ``static_pairs`` is the over-approximate set the model-gap
    direction checks against (``concurrency.runtime_pairs``: one-hop
    edges closed over the class-call graph).  ``coverage_pairs``, when
    given, is the tighter set the coverage direction reports on
    (``model["expanded"]``) — diffing coverage against the closure
    would drown the report in never-acquirable pairs."""
    static = {tuple(p) for p in static_pairs}
    coverage = static if coverage_pairs is None else {
        tuple(p) for p in coverage_pairs}
    observed = {(e["outer"], e["inner"]) for e in rep.get("edges", ())
                if e.get("resolved")}
    unresolved = [(e["outer"], e["inner"])
                  for e in rep.get("edges", ()) if not e.get("resolved")]
    return {
        "observed_not_modeled": sorted(observed - static),
        "modeled_not_observed": sorted(coverage - observed),
        "unresolved_observed": sorted(unresolved),
    }


def reset() -> None:
    """Drop collected state (between gate phases in one process)."""
    global _state
    if _state is None:
        return
    was_installed = installed()
    if was_installed:
        uninstall()
    _state = None
    if was_installed:
        install()
