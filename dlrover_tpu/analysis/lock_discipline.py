"""Pass 2: lockset analysis over threaded master/agent classes.

For every class owning a ``threading.Lock``/``RLock``/``Condition`` (and
for module-level locks), the pass tracks which locks are lexically held at
every ``self.<attr>`` access, *learns* which lock guards which attribute
from majority usage, and reports:

GL201  an access to an attribute that is guarded almost everywhere else,
       made without the lock.
GL202  two locks nested in both orders anywhere in the module (deadlock).
GL203  a blocking call (sleep / subprocess / HTTP / thread join) made
       while holding a lock.
GL204  a bare ``lock.acquire()`` outside a ``with`` statement.
GL205  an attribute written by several methods of a lock-owning class
       that is *never* accessed under any lock.

The codebase convention "helper with the lock held" (private methods
called only from inside critical sections, e.g.
``RendezvousManager._cut_round``) is handled interprocedurally: a private
method's *entry lockset* is the intersection of the locksets at its
internal call sites, computed to fixpoint, and classes are merged with
their same-module base classes so inherited helpers see subclass call
sites too.

Accesses inside nested ``def``s (thread targets, closures) are analyzed
with an EMPTY lockset — they run later, on another thread.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dlrover_tpu.analysis.findings import Finding
from dlrover_tpu.analysis.trace_safety import (
    _dotted_name,
    _import_aliases,
)

_LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}
_BLOCKING_EXACT = {"time.sleep"}
_BLOCKING_PREFIX = ("subprocess.", "requests.", "urllib.request.",
                    "socket.create_connection")
_THREADY = ("thread", "proc", "worker", "server")
_SKIP_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}

# -- GL501: the gradient-path lock owners ----------------------------------
# Classes/modules whose locks sit on per-step paths: the KV store's
# condition (every dcn/ exchange), the mutation log it calls into, the
# cross-slice sync, and the per-step timeline. Blocking ops under THESE
# locks — including via "(lock held)" helpers — are per-step stalls.
# `# graftlint: hot-path` on a class def line opts additional classes in.
_HOT_CLASS_NAMES = {"KVStoreService", "MutationLog", "SliceGradSync",
                    "StepTimeline"}
_HOT_MODULE_SUFFIXES = ("parallel/dcn_sync.py",)
_HOT_MARKER_RE = re.compile(r"#\s*graftlint:\s*hot-path\b")

# the EXTENDED blocking vocabulary GL501 adds on top of GL203's: file
# I/O, fsync/rename, socket traffic and RPC-ish client calls — things
# that are fine under an ordinary lock but not under a hot one
_BLOCKING_OS_EXACT = {"os.fsync", "os.replace", "os.rename",
                      "os.remove", "os.fdatasync"}
_FILEY_RECEIVERS = ("file", "sock", "conn", "log", "_fh", "_fd")
_FILEY_METHODS = {"write", "flush", "read", "readline", "readlines",
                  "truncate", "seek", "close"}
_SOCKY_METHODS = {"send", "sendall", "recv", "recv_into", "connect",
                  "accept"}
_RPC_RECEIVERS = ("client", "stub", "channel")

# guard inference thresholds: an attribute is "guarded by L" when at least
# _MIN_GUARDED accesses hold L and they are at least _GUARDED_RATIO of all
# accesses outside __init__
_MIN_GUARDED = 2
_GUARDED_RATIO = 0.75


@dataclasses.dataclass
class _Access:
    attr: str
    held: Tuple[str, ...]
    line: int
    col: int
    method: str
    is_write: bool
    in_nested_def: bool


@dataclasses.dataclass
class _CallSite:
    callee: str               # bare method name
    held: Tuple[str, ...]
    caller: str


class _ModuleOwner:
    """Duck-typed _ClassFamily stand-in for module-level functions: no
    instance attrs or methods, only module-level locks resolve."""

    def __init__(self, aliases: Dict[str, str], module_locks: Set[str]):
        self.aliases = aliases
        self.module_locks = module_locks
        self.lock_attrs: Set[str] = set()
        self.method_names: Set[str] = set()

    def lock_id(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"<module>.{expr.id}"
        return None


def group_class_families(
        classes: List[ast.ClassDef]
) -> List[Tuple[str, List[ast.ClassDef]]]:
    """Union-find grouping of classes with their same-module bases —
    shared by the lock-discipline and state-roundtrip passes so both
    see inherited helpers (``_export_extra`` overrides, private
    lock-held helpers) next to their base-class call sites."""
    by_name = {c.name: c for c in classes}
    parent: Dict[str, str] = {c.name: c.name for c in classes}

    def find(x: str) -> str:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for c in classes:
        for base in c.bases:
            if isinstance(base, ast.Name) and base.id in by_name:
                parent[find(c.name)] = find(base.id)
    groups: Dict[str, List[ast.ClassDef]] = {}
    for c in classes:
        groups.setdefault(find(c.name), []).append(c)
    return sorted(groups.items())


def _module_lock_names(tree: ast.Module,
                       aliases: Dict[str, str]) -> Set[str]:
    """Names bound to threading lock objects at module scope."""
    names: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call):
            head = _dotted_name(node.value.func, aliases)
            if head in _LOCK_FACTORIES:
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        names.add(tgt.id)
    return names


class _MethodScan(ast.NodeVisitor):
    """Walk one method body tracking the lexically-held lock stack."""

    def __init__(self, owner: "_ClassFamily", method_name: str):
        self.owner = owner
        self.method = method_name
        self.held: List[str] = []
        self.accesses: List[_Access] = []
        self.calls: List[_CallSite] = []
        self.order_pairs: List[Tuple[str, str, ast.AST, str]] = []
        self.blocking: List[Tuple[str, ast.Call, Tuple[str, ...]]] = []
        # EVERY blocking-ish call (classic + extended vocabulary),
        # recorded regardless of the lexical lockset so the hot-path
        # pass can join it with the method's interprocedural entry
        # lockset: (name, kind, node, lexically_held, in_nested_def)
        self.blocking_all: List[Tuple[str, str, ast.Call,
                                      Tuple[str, ...], bool]] = []
        self.bare_acquires: List[ast.Call] = []
        self._nested_depth = 0

    # -- helpers -----------------------------------------------------------
    def _lock_id(self, expr: ast.AST) -> Optional[str]:
        return self.owner.lock_id(expr)

    # -- visitors ----------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            lock = self._lock_id(item.context_expr)
            if lock is not None:
                for outer in self.held:
                    if outer != lock:
                        self.order_pairs.append(
                            (outer, lock, item.context_expr, self.method))
                self.held.append(lock)
                pushed += 1
            else:
                # `with self._lock, open(self._path):` — item i runs with
                # the locks of items < i already acquired
                self.visit(item.context_expr)
                if item.optional_vars is not None:
                    self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._scan_nested(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._scan_nested(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        self._scan_nested(node)

    def _scan_nested(self, node: ast.AST) -> None:
        """A nested def runs later (often on another thread): empty
        lockset, and its accesses don't inherit the method entry set."""
        saved, self.held = self.held, []
        self._nested_depth += 1
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self._nested_depth -= 1
        self.held = saved

    def visit_Attribute(self, node: ast.Attribute) -> None:
        root = node.value
        if isinstance(root, ast.Name) and root.id in ("self", "cls"):
            attr = node.attr
            if (attr not in self.owner.lock_attrs
                    and attr not in self.owner.method_names):
                self.accesses.append(_Access(
                    attr=attr,
                    held=tuple(self.held),
                    line=node.lineno, col=node.col_offset,
                    method=self.method,
                    is_write=isinstance(node.ctx, (ast.Store, ast.Del)),
                    in_nested_def=self._nested_depth > 0,
                ))
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        # self.method(...) / super().method(...) -> propagation edge
        if isinstance(func, ast.Attribute):
            base = func.value
            is_self = isinstance(base, ast.Name) and base.id in ("self",
                                                                 "cls")
            is_super = (isinstance(base, ast.Call)
                        and isinstance(base.func, ast.Name)
                        and base.func.id == "super")
            if (is_self or is_super) and \
                    func.attr in self.owner.method_names:
                if self._nested_depth == 0:
                    self.calls.append(_CallSite(
                        callee=func.attr, held=tuple(self.held),
                        caller=self.method))
            if (func.attr == "acquire"
                    and self._lock_id(base) is not None
                    and not node.args and not node.keywords):
                # acquire(timeout=...) / acquire(blocking=False) cannot be
                # expressed as a `with` statement — only the bare form is
                # the discipline violation
                self.bare_acquires.append(node)
        name = self._blocking_name(node)
        if name and self.held:
            self.blocking.append((name, node, tuple(self.held)))
        if name:
            self.blocking_all.append((name, "blocking", node,
                                      tuple(self.held),
                                      self._nested_depth > 0))
        else:
            ext = self._extended_blocking(node)
            if ext:
                ext_name, kind = ext
                self.blocking_all.append((ext_name, kind, node,
                                          tuple(self.held),
                                          self._nested_depth > 0))
        self.generic_visit(node)

    def _extended_blocking(
            self, node: ast.Call) -> Optional[Tuple[str, str]]:
        """GL501's wider vocabulary: file I/O, fsync/rename, socket
        traffic and RPC-ish client calls — acceptable under an ordinary
        lock, never under a gradient-path one."""
        head = _dotted_name(node.func, self.owner.aliases)
        if head == "open":
            return "open", "file I/O"
        if head in _BLOCKING_OS_EXACT:
            return head, "file I/O"
        if head and head.startswith("socket."):
            return head, "socket"
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            text = ""
            if isinstance(recv, ast.Attribute):
                text = recv.attr.lower()
            elif isinstance(recv, ast.Name):
                text = recv.id.lower()
            meth = node.func.attr
            if meth in _SOCKY_METHODS and any(
                    t in text for t in ("sock", "conn")):
                return f"{text}.{meth}", "socket"
            if meth in _FILEY_METHODS and any(
                    t in text for t in _FILEY_RECEIVERS):
                return f"{text}.{meth}", "file I/O"
            if any(t in text for t in _RPC_RECEIVERS):
                return f"{text}.{meth}", "RPC"
        return None

    def _blocking_name(self, node: ast.Call) -> Optional[str]:
        head = _dotted_name(node.func, self.owner.aliases)
        if head in _BLOCKING_EXACT:
            return head
        if head and head.startswith(_BLOCKING_PREFIX):
            return head
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            recv = node.func.value
            text = ""
            if isinstance(recv, ast.Attribute):
                text = recv.attr
            elif isinstance(recv, ast.Name):
                text = recv.id
            if any(t in text.lower() for t in _THREADY):
                return f"{text}.join"
        return None


class _ClassFamily:
    """A class merged with its same-module base classes."""

    def __init__(self, name: str, classes: List[ast.ClassDef],
                 aliases: Dict[str, str], relpath: str,
                 module_locks: Optional[Set[str]] = None):
        self.name = name
        self.classes = classes
        self.aliases = aliases
        self.relpath = relpath
        self.module_locks = module_locks or set()
        self.lock_attrs: Set[str] = set()
        self.method_names: Set[str] = set()
        self.methods: List[Tuple[ast.ClassDef, ast.FunctionDef]] = []
        for cls in classes:
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    self.method_names.add(item.name)
                    self.methods.append((cls, item))
                elif isinstance(item, ast.Assign):
                    # class-level lock (e.g. Context._lock)
                    if self._is_lock_factory(item.value):
                        for tgt in item.targets:
                            if isinstance(tgt, ast.Name):
                                self.lock_attrs.add(tgt.id)
        # instance attributes: anything ever STORED via self.X/cls.X —
        # class-body constants (e.g. `name = "base"`) never race and are
        # excluded from guard inference
        self.instance_attrs: Set[str] = set()
        for _, meth in self.methods:
            for node in ast.walk(meth):
                if isinstance(node, ast.Assign) and \
                        self._is_lock_factory(node.value):
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id in ("self", "cls")):
                            self.lock_attrs.add(tgt.attr)
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, (ast.Store, ast.Del))
                        and isinstance(node.value, ast.Name)
                        and node.value.id in ("self", "cls")):
                    self.instance_attrs.add(node.attr)

    def _is_lock_factory(self, expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        head = _dotted_name(expr.func, self.aliases)
        return head in _LOCK_FACTORIES

    def lock_id(self, expr: ast.AST) -> Optional[str]:
        """'self._lock' / 'cls._lock' / 'ClassName._lock' -> qualified id;
        a bare module-level lock name resolves to '<module>.<name>'."""
        if isinstance(expr, ast.Name) and expr.id in self.module_locks:
            return f"<module>.{expr.id}"
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id in ("self", "cls") and \
                        expr.attr in self.lock_attrs:
                    return f"{self.name}.{expr.attr}"
                if base.id in {c.name for c in self.classes} and \
                        expr.attr in self.lock_attrs:
                    return f"{self.name}.{expr.attr}"
        return None

    def owns_locks(self) -> bool:
        return bool(self.lock_attrs)


def entry_locksets(
        scans: Dict[str, _MethodScan]) -> Dict[str, frozenset]:
    """Fixpoint: a private method's entry lockset is the intersection
    of held locksets at its internal call sites. Shared with the
    graftrace concurrency pass, which joins the same "(lock held)"
    helper propagation into its project-wide lock-order graph."""
    sites: Dict[str, List[_CallSite]] = {}
    for scan in scans.values():
        for cs in scan.calls:
            sites.setdefault(cs.callee, []).append(cs)

    memo: Dict[str, frozenset] = {}

    def entry(meth: str, stack: Set[str]) -> frozenset:
        if meth in memo:
            return memo[meth]
        if not meth.startswith("_") or meth.startswith("__"):
            memo[meth] = frozenset()
            return memo[meth]
        call_sites = sites.get(meth)
        if not call_sites:
            memo[meth] = frozenset()
            return memo[meth]
        if meth in stack:
            return frozenset()   # cycle: no caller contribution
        acc: Optional[frozenset] = None
        for cs in call_sites:
            held = frozenset(cs.held) | entry(cs.caller,
                                              stack | {meth})
            acc = held if acc is None else (acc & held)
        memo[meth] = acc or frozenset()
        return memo[meth]

    return {m: entry(m, set())
            for m in {s.split(".", 1)[1] for s in scans}}


class LockDisciplinePass:
    def run(self, relpath: str, tree: ast.Module,
            source_lines: Sequence[str]) -> List[Finding]:
        aliases = _import_aliases(tree)
        findings: List[Finding] = []
        order_pairs: List[Tuple[str, str, ast.AST, str]] = []
        module_locks = _module_lock_names(tree, aliases)
        marker_lines = {i for i, ln in enumerate(source_lines, start=1)
                        if _HOT_MARKER_RE.search(ln)}
        hot_module = relpath.endswith(_HOT_MODULE_SUFFIXES)

        classes = [n for n in tree.body if isinstance(n, ast.ClassDef)]
        for family in self._families(classes, aliases, relpath,
                                     module_locks):
            if not family.owns_locks() and not module_locks:
                continue
            hot = hot_module or any(
                cls.name in _HOT_CLASS_NAMES
                or cls.lineno in marker_lines
                for cls in family.classes)
            findings.extend(
                self._analyze_family(family, order_pairs, hot=hot))
        findings.extend(self._module_level(tree, aliases, relpath,
                                           module_locks, order_pairs,
                                           hot=hot_module))
        findings.extend(self._inversions(order_pairs, relpath))
        return findings

    # -- family construction ----------------------------------------------
    def _families(self, classes: List[ast.ClassDef],
                  aliases: Dict[str, str], relpath: str,
                  module_locks: Set[str]) -> List[_ClassFamily]:
        return [
            _ClassFamily(root, members, aliases, relpath, module_locks)
            for root, members in group_class_families(classes)
        ]

    # -- per-family analysis ----------------------------------------------
    def _analyze_family(
            self, family: _ClassFamily,
            order_pairs: List[Tuple[str, str, ast.AST, str]],
            hot: bool = False,
    ) -> List[Finding]:
        findings: List[Finding] = []
        scans: Dict[str, _MethodScan] = {}
        for cls, meth in family.methods:
            scan = _MethodScan(family, meth.name)
            for stmt in meth.body:
                scan.visit(stmt)
            # later defs of the same name (subclass overrides) merge:
            # both bodies belong to the family's behavior
            key = f"{cls.name}.{meth.name}"
            scans[key] = scan
            order_pairs.extend(scan.order_pairs)
            for name, node, held in scan.blocking:
                findings.append(Finding(
                    "GL203", family.relpath, node.lineno, node.col_offset,
                    f"blocking call `{name}` while holding "
                    f"{', '.join(held)} in {key}", symbol=key))
            for node in scan.bare_acquires:
                findings.append(Finding(
                    "GL204", family.relpath, node.lineno, node.col_offset,
                    f"bare .acquire() outside `with` in {key}",
                    symbol=key))

        # classes that never actually take any lock (but live in a module
        # with a module-level lock) get no guard inference: GL205 on them
        # would flag plain single-threaded state
        uses_locks = bool(family.lock_attrs) or any(
            scan.bare_acquires or scan.blocking or scan.order_pairs
            or any(acc.held for acc in scan.accesses)
            or any(cs.held for cs in scan.calls)
            for scan in scans.values())
        if not uses_locks:
            return findings

        entries = self._entry_locksets(family, scans)

        if hot:
            findings.extend(self._hot_path_blocking(family, scans,
                                                    entries))

        # effective locksets per access
        accesses: List[_Access] = []
        for key, scan in scans.items():
            meth_name = key.split(".", 1)[1]
            if meth_name in _SKIP_METHODS:
                continue
            entry = entries.get(meth_name, frozenset())
            for acc in scan.accesses:
                held = set(acc.held)
                if not acc.in_nested_def:
                    held |= entry
                accesses.append(dataclasses.replace(
                    acc, held=tuple(sorted(held)),
                    method=key))

        findings.extend(self._infer_guards(family, accesses))
        findings.extend(self._never_guarded(family, accesses))
        return findings

    # -- GL501 --------------------------------------------------------------
    def _hot_path_blocking(
            self, family: _ClassFamily,
            scans: Dict[str, "_MethodScan"],
            entries: Dict[str, frozenset]) -> List[Finding]:
        """Blocking ops (extended vocabulary) whose EFFECTIVE lockset —
        lexical ∪ the method's interprocedural entry lockset — is
        non-empty, in a gradient-path lock owner. The entry-lockset
        machinery is the same "(lock held)" helper propagation GL201
        uses, so indirection can't hide a sync write."""
        findings: List[Finding] = []
        for key, scan in scans.items():
            meth_name = key.split(".", 1)[1]
            entry = entries.get(meth_name, frozenset())
            for name, kind, node, held, nested in scan.blocking_all:
                effective = set(held)
                if not nested:
                    effective |= entry
                if not effective:
                    continue
                if kind == "blocking" and held:
                    continue      # GL203 already reports this one
                via = ("" if held else
                       " (lock held at every call site of this helper)")
                findings.append(Finding(
                    "GL501", family.relpath, node.lineno,
                    node.col_offset,
                    f"{kind} `{name}` under gradient-path lock "
                    f"{', '.join(sorted(effective))} in {key}{via} — "
                    f"a per-step stall", symbol=key))
        return findings

    def _entry_locksets(
            self, family: _ClassFamily,
            scans: Dict[str, _MethodScan]) -> Dict[str, frozenset]:
        return entry_locksets(scans)

    def _infer_guards(self, family: _ClassFamily,
                      accesses: List[_Access]) -> List[Finding]:
        findings: List[Finding] = []
        by_attr: Dict[str, List[_Access]] = {}
        for acc in accesses:
            by_attr.setdefault(acc.attr, []).append(acc)
        for attr, accs in sorted(by_attr.items()):
            if attr not in family.instance_attrs:
                continue
            total = len(accs)
            counts: Dict[str, int] = {}
            for acc in accs:
                for lock in acc.held:
                    counts[lock] = counts.get(lock, 0) + 1
            if not counts:
                continue
            lock, guarded = max(counts.items(), key=lambda kv: kv[1])
            if guarded < _MIN_GUARDED or guarded >= total or \
                    guarded / total < _GUARDED_RATIO:
                continue
            for acc in accs:
                if lock in acc.held:
                    continue
                kind = "write" if acc.is_write else "read"
                findings.append(Finding(
                    "GL201", family.relpath, acc.line, acc.col,
                    f"unguarded {kind} of '{family.name}.{attr}' "
                    f"(guarded by {lock} in {guarded}/{total} accesses) "
                    f"in {acc.method}", symbol=acc.method))
        return findings

    def _never_guarded(self, family: _ClassFamily,
                       accesses: List[_Access]) -> List[Finding]:
        findings: List[Finding] = []
        by_attr: Dict[str, List[_Access]] = {}
        for acc in accesses:
            by_attr.setdefault(acc.attr, []).append(acc)
        for attr, accs in sorted(by_attr.items()):
            if attr not in family.instance_attrs:
                continue
            if any(acc.held for acc in accs):
                continue
            writers = {acc.method for acc in accs if acc.is_write}
            if len(writers) < 2:
                continue
            for acc in accs:
                if not acc.is_write:
                    continue
                findings.append(Finding(
                    "GL205", family.relpath, acc.line, acc.col,
                    f"'{family.name}.{attr}' is written from "
                    f"{len(writers)} methods of a lock-owning class but "
                    f"never accessed under a lock", symbol=acc.method))
        return findings

    # -- module-level locks ------------------------------------------------
    def _module_level(
            self, tree: ast.Module, aliases: Dict[str, str], relpath: str,
            lock_names: Set[str],
            order_pairs: List[Tuple[str, str, ast.AST, str]],
            hot: bool = False,
    ) -> List[Finding]:
        """Module-level functions using module-level locks, analyzed with
        the SAME _MethodScan walker the class pass uses (one copy of the
        lock-stack / blocking-call / bare-acquire logic)."""
        if not lock_names:
            return []
        owner = _ModuleOwner(aliases, lock_names)
        findings: List[Finding] = []
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            scan = _MethodScan(owner, node.name)
            for stmt in node.body:
                scan.visit(stmt)
            order_pairs.extend(scan.order_pairs)
            for name, cnode, held in scan.blocking:
                findings.append(Finding(
                    "GL203", relpath, cnode.lineno, cnode.col_offset,
                    f"blocking call `{name}` while holding "
                    f"{', '.join(held)} in {node.name}",
                    symbol=node.name))
            if hot:
                for name, kind, cnode, held, _ in scan.blocking_all:
                    if not held or (kind == "blocking" and held):
                        continue
                    findings.append(Finding(
                        "GL501", relpath, cnode.lineno,
                        cnode.col_offset,
                        f"{kind} `{name}` under gradient-path lock "
                        f"{', '.join(sorted(held))} in {node.name} — "
                        f"a per-step stall", symbol=node.name))
            for cnode in scan.bare_acquires:
                findings.append(Finding(
                    "GL204", relpath, cnode.lineno, cnode.col_offset,
                    f"bare .acquire() outside `with` in {node.name}",
                    symbol=node.name))
        return findings

    # -- GL202 --------------------------------------------------------------
    def _inversions(
            self, order_pairs: List[Tuple[str, str, ast.AST, str]],
            relpath: str) -> List[Finding]:
        findings: List[Finding] = []
        seen: Dict[Tuple[str, str], Tuple[ast.AST, str]] = {}
        reported: Set[frozenset] = set()
        for a, b, node, method in order_pairs:
            seen.setdefault((a, b), (node, method))
        for (a, b), (node, method) in sorted(
                seen.items(), key=lambda kv: kv[1][0].lineno):
            if (b, a) in seen and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                other_node, other_method = seen[(b, a)]
                # report at the LATER site (where the inversion appears),
                # citing the established order
                if other_node.lineno > node.lineno:
                    node, other_node = other_node, node
                    method, other_method = other_method, method
                    a, b = b, a
                findings.append(Finding(
                    "GL202", relpath, node.lineno, node.col_offset,
                    f"lock order inversion: {a} -> {b} here but "
                    f"{b} -> {a} at line {other_node.lineno} "
                    f"({other_method})", symbol=method))
        return findings
