"""Pass 1: trace-safety analysis of jitted/shard_mapped functions.

Resolves the set of *traced* functions per module — functions passed to
``jax.jit`` / ``pjit`` / ``shard_map`` / ``jax.eval_shape`` (as decorators
or call arguments, through ``functools.partial`` and ``self.method``
references), plus everything they call or hand to ``lax.scan``-style
combinators within the module — then checks four invariants elastic
re-lowering depends on:

GL101  Python ``if``/``while`` on a traced argument (taint-propagated;
       static shape/dtype/``is None`` tests are exempt — those resolve at
       trace time).
GL102  impure calls (``time.*``, ``np.random.*``, ``random.*``,
       ``os.environ``, ``print``/``open``/``input``) inside traced code.
GL103  mutation of enclosing state (``global``/``nonlocal``, ``self.x =``,
       container mutation of closure/module names) inside traced code.
GL104  a ``jax.jit`` whose target threads state-like parameters but the
       call carries no ``donate_argnums``/``donate_argnames``.
GL105  ``device_get``/``block_until_ready``/``.item()`` lexically inside a
       loop in hot-path modules (``trainer/``) — a per-step host sync.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple, Union

from dlrover_tpu.analysis.findings import Finding

FunctionNode = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]

# call heads (alias-normalized dotted names) that trace their first
# positional argument
_JIT_HEADS = {"jax.jit", "jit", "jax.pjit", "pjit",
              "jax.experimental.pjit.pjit"}
_TRACING_HEADS = _JIT_HEADS | {
    "shard_map", "jax.shard_map", "jax.experimental.shard_map.shard_map",
    "jax.eval_shape",
}
# combinators whose function-valued arguments are traced when reached
# from traced code
_COMBINATOR_HEADS = {
    "jax.lax.scan", "jax.lax.fori_loop", "jax.lax.while_loop",
    "jax.lax.cond", "jax.lax.switch", "jax.lax.associative_scan",
    "jax.vmap", "jax.grad", "jax.value_and_grad", "jax.vjp", "jax.jvp",
    "jax.checkpoint", "jax.remat", "jax.custom_vjp", "jax.custom_jvp",
}

_IMPURE_EXACT = {
    "time.time", "time.monotonic", "time.perf_counter", "time.time_ns",
    "time.sleep", "time.monotonic_ns", "datetime.datetime.now",
    "datetime.datetime.utcnow", "os.getenv", "os.urandom", "uuid.uuid4",
}
_IMPURE_PREFIX = ("numpy.random.", "random.", "os.environ")
_IMPURE_BUILTINS = {"print", "open", "input"}
_PURE_EXEMPT = {"jax.debug.print", "jax.debug.callback"}

_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}
_STATIC_FUNCS = {"len", "isinstance", "hasattr", "getattr", "type",
                 "callable", "typeof"}
_STATE_PARAM_EXACT = {"state", "train_state", "carry", "opt_state"}
_STATE_PARAM_SUFFIX = ("_state", "_opt")
_MUTATING_METHODS = {"append", "extend", "add", "update", "setdefault",
                     "insert", "remove", "clear", "pop", "popitem",
                     "discard", "appendleft", "extendleft"}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_SYNC_METHODS = {"block_until_ready", "item"}


def _build_parents(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def _import_aliases(tree: ast.Module) -> Dict[str, str]:
    """local name -> dotted real name, from module-level-ish imports."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _dotted_name(node: ast.AST,
                 aliases: Dict[str, str]) -> Optional[str]:
    """'np.random.normal' -> 'numpy.random.normal' (root alias-resolved)."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if not isinstance(cur, ast.Name):
        return None
    root = aliases.get(cur.id, cur.id)
    parts.append(root)
    return ".".join(reversed(parts))


class _Scopes:
    """Name -> FunctionDef resolution through lexical scopes, plus
    class-method resolution for `self.f` references."""

    def __init__(self, tree: ast.Module,
                 parents: Dict[ast.AST, ast.AST]):
        self._parents = parents
        self._defs: Dict[int, Dict[str, FunctionNode]] = {}
        self._methods: Dict[ast.ClassDef, Dict[str, ast.FunctionDef]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                scope = self._enclosing_scope(node)
                self._defs.setdefault(id(scope), {})[node.name] = node
                if isinstance(scope, ast.ClassDef):
                    self._methods.setdefault(scope, {})[node.name] = node
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Lambda):
                scope = self._enclosing_scope(node)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        self._defs.setdefault(
                            id(scope), {})[tgt.id] = node.value

    def _enclosing_scope(self, node: ast.AST) -> ast.AST:
        cur = self._parents.get(node)
        while cur is not None and not isinstance(
                cur, (ast.Module, ast.FunctionDef, ast.AsyncFunctionDef,
                      ast.ClassDef)):
            cur = self._parents.get(cur)
        return cur

    def enclosing_class(self, node: ast.AST) -> Optional[ast.ClassDef]:
        cur: Optional[ast.AST] = node
        while cur is not None:
            cur = self._parents.get(cur)
            if isinstance(cur, ast.ClassDef):
                return cur
        return None

    def resolve(self, expr: ast.AST,
                from_node: ast.AST) -> Optional[FunctionNode]:
        """Resolve a function-valued expression to its def, or None."""
        fn, _ = self.resolve_with_bound(expr, from_node)
        return fn

    def resolve_with_bound(
            self, expr: ast.AST, from_node: ast.AST
    ) -> Tuple[Optional[FunctionNode], Set[str]]:
        """Like resolve, additionally returning parameter names bound by
        ``functools.partial`` — those are Python constants at trace time
        (static), not tracers."""
        if isinstance(expr, ast.Call):
            head = _dotted_name(expr.func, {})
            if head and head.split(".")[-1] == "partial" and expr.args:
                fn, inner_bound = self.resolve_with_bound(
                    expr.args[0], from_node)
                if fn is None:
                    return None, set()
                bound = set(inner_bound)
                params = _fn_params(fn)
                bound.update(params[:len(expr.args) - 1])
                bound.update(kw.arg for kw in expr.keywords if kw.arg)
                return fn, bound
            return None, set()
        return self._resolve_plain(expr, from_node), set()

    def _resolve_plain(self, expr: ast.AST,
                       from_node: ast.AST) -> Optional[FunctionNode]:
        if isinstance(expr, ast.Lambda):
            return expr
        if isinstance(expr, ast.Attribute) and isinstance(
                expr.value, ast.Name) and expr.value.id in ("self", "cls"):
            cls = self.enclosing_class(from_node)
            if cls is not None:
                return self._methods.get(cls, {}).get(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            scope: Optional[ast.AST] = self._enclosing_scope(from_node)
            while scope is not None:
                found = self._defs.get(id(scope), {}).get(expr.id)
                if found is not None:
                    return found
                if isinstance(scope, ast.Module):
                    break
                scope = self._enclosing_scope(scope)
            return None
        return None


def _jit_kwargs(call: ast.Call) -> Dict[str, ast.expr]:
    return {kw.arg: kw.value for kw in call.keywords if kw.arg}


def _static_param_names(call: Optional[ast.Call],
                        fn: FunctionNode) -> Set[str]:
    """Names of params marked static via static_argnums/static_argnames."""
    if call is None:
        return set()
    kwargs = _jit_kwargs(call)
    names: Set[str] = set()
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    num_expr = kwargs.get("static_argnums")
    if num_expr is not None:
        for n in ast.walk(num_expr):
            if isinstance(n, ast.Constant) and isinstance(n.value, int):
                if 0 <= n.value < len(params):
                    names.add(params[n.value])
    name_expr = kwargs.get("static_argnames")
    if name_expr is not None:
        for n in ast.walk(name_expr):
            if isinstance(n, ast.Constant) and isinstance(n.value, str):
                names.add(n.value)
    return names


def _fn_params(fn: FunctionNode) -> List[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _qualname(fn: FunctionNode, scopes: _Scopes) -> str:
    if isinstance(fn, ast.Lambda):
        return "<lambda>"
    cls = scopes.enclosing_class(fn)
    return f"{cls.name}.{fn.name}" if cls else fn.name


class TraceSafetyPass:
    """Analyze one parsed module; returns findings."""

    def __init__(self, hot_path_prefixes: Sequence[str] = ("trainer/",)):
        self._hot_prefixes = tuple(hot_path_prefixes)

    def run(self, relpath: str, tree: ast.Module,
            source_lines: Sequence[str]) -> List[Finding]:
        self.relpath = relpath
        self.aliases = _import_aliases(tree)
        self.parents = _build_parents(tree)
        self.scopes = _Scopes(tree, self.parents)
        findings: List[Finding] = []
        traced = self._collect_traced(tree, findings)
        for fn, tainted_params in traced.items():
            findings.extend(self._check_traced_fn(fn, tainted_params))
        findings.extend(self._check_hot_loop_sync(tree))
        return findings

    # -- traced-set resolution --------------------------------------------
    def _collect_traced(
            self, tree: ast.Module, findings: List[Finding]
    ) -> Dict[FunctionNode, Set[str]]:
        """Map traced function -> set of TAINTED (tracer-valued) params.

        Roots get all params minus static_argnums/static_argnames and
        partial-bound names. Transitive callees get taint mapped through
        call-site arguments: a param receiving a static closure value
        stays untainted (fit_block(x, block=128) branches on Python ints,
        not tracers). Functions passed as *values* to combinators
        (lax.scan bodies) conservatively taint every param.
        """
        roots: List[Tuple[FunctionNode, Set[str]]] = []

        def add_root(fn: FunctionNode, bound: Set[str],
                     call: Optional[ast.Call]) -> None:
            static = bound | _static_param_names(call, fn)
            roots.append((fn, set(_fn_params(fn)) - static))

        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                head = _dotted_name(node.func, self.aliases)
                if head in _TRACING_HEADS and node.args:
                    fn, bound = self.scopes.resolve_with_bound(
                        node.args[0], node)
                    if fn is not None:
                        add_root(fn, bound, node)
                        if head in _JIT_HEADS:
                            self._check_donation(node, fn, findings)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for deco in node.decorator_list:
                    head = _dotted_name(deco, self.aliases)
                    if head in _TRACING_HEADS:
                        add_root(node, set(), None)
                        if head in _JIT_HEADS:
                            self._check_donation(None, node, findings,
                                                 deco_line=deco.lineno)
                    elif isinstance(deco, ast.Call):
                        inner = _dotted_name(deco.func, self.aliases)
                        inner_last = (inner or "").split(".")[-1]
                        if inner in _TRACING_HEADS:
                            add_root(node, set(), deco)
                            if inner in _JIT_HEADS:
                                self._check_donation(deco, node, findings)
                        elif inner_last == "partial" and deco.args:
                            part_head = _dotted_name(deco.args[0],
                                                     self.aliases)
                            if part_head in _TRACING_HEADS:
                                add_root(node, set(), deco)
                                if part_head in _JIT_HEADS:
                                    self._check_donation(deco, node,
                                                         findings)

        traced: Dict[FunctionNode, Set[str]] = {}
        work = list(roots)
        while work:
            fn, tainted_params = work.pop()
            known = traced.get(fn)
            if known is not None and tainted_params <= known:
                continue
            traced[fn] = (known or set()) | tainted_params
            tainted = set(traced[fn])
            body = fn.body if isinstance(fn.body, list) else [fn.body]
            self._propagate_taint(body, tainted)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee, bound = self.scopes.resolve_with_bound(
                    node.func, node)
                if callee is not None and callee is not fn:
                    work.append(
                        (callee,
                         self._map_call_taint(node, callee, tainted)
                         - bound))
                head = _dotted_name(node.func, self.aliases)
                if head in _COMBINATOR_HEADS or head in _TRACING_HEADS:
                    for arg in node.args:
                        sub, sub_bound = self.scopes.resolve_with_bound(
                            arg, node)
                        if sub is not None and sub is not fn:
                            work.append(
                                (sub,
                                 set(_fn_params(sub)) - sub_bound))
        return traced

    def _map_call_taint(self, call: ast.Call, callee: FunctionNode,
                        caller_tainted: Set[str]) -> Set[str]:
        """Which callee params receive tainted values at this call."""
        params = _fn_params(callee)
        out: Set[str] = set()
        for i, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                # can't track positions past a splat: taint the rest
                out.update(params[i:])
                break
            if i < len(params) and self._expr_taints(arg, caller_tainted):
                out.add(params[i])
        for kw in call.keywords:
            if kw.arg is None:
                continue          # **kwargs splat: unknown names, skip
            if kw.arg in params and self._expr_taints(kw.value,
                                                      caller_tainted):
                out.add(kw.arg)
        return out

    # -- GL104 -------------------------------------------------------------
    def _check_donation(self, call: Optional[ast.Call], fn: FunctionNode,
                        findings: List[Finding],
                        deco_line: Optional[int] = None) -> None:
        kwargs = _jit_kwargs(call) if call is not None else {}
        if "donate_argnums" in kwargs or "donate_argnames" in kwargs:
            return
        stateful = [
            p for p in _fn_params(fn)
            if p in _STATE_PARAM_EXACT or p.endswith(_STATE_PARAM_SUFFIX)
        ]
        if not stateful:
            return
        if not self._threads_state(fn, set(stateful)):
            # read-only use (eval/metrics): the state is NOT returned
            # updated, so donating it would invalidate the caller's copy
            return
        node = call if call is not None else fn
        line = deco_line if deco_line is not None else node.lineno
        findings.append(Finding(
            "GL104", self.relpath, line,
            getattr(node, "col_offset", 0),
            f"jit of '{_qualname(fn, self.scopes)}' threads state-like "
            f"parameters ({', '.join(stateful)}) but passes no "
            f"donate_argnums/donate_argnames",
            symbol=_qualname(fn, self.scopes)))

    def _threads_state(self, fn: FunctionNode,
                       state_params: Set[str]) -> bool:
        """True when the function RETURNS updated state: some top-level
        return value (or tuple element) is a bare name tainted by a
        state-like param. `return loss.sum()` (read-only eval) is not
        threading; `return new_state, metrics` is."""
        if isinstance(fn, ast.Lambda):
            body_stmts: List[ast.stmt] = []
            returns: List[ast.expr] = [fn.body]
        else:
            body_stmts = fn.body
            returns = []
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    owner = self._enclosing_function(node)
                    if owner is fn:
                        returns.append(node.value)
        tainted = set(state_params)
        self._propagate_taint(body_stmts, tainted)
        for value in returns:
            elements = (value.elts if isinstance(value, ast.Tuple)
                        else [value])
            for el in elements:
                if isinstance(el, ast.Name) and el.id in tainted:
                    return True
        return False

    # -- per-function checks (GL101/102/103) -------------------------------
    def _check_traced_fn(self, fn: FunctionNode,
                         tainted_params: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        qual = _qualname(fn, self.scopes)
        params = set(tainted_params)
        body = fn.body if isinstance(fn.body, list) else [fn.body]

        # locals: every name stored anywhere in the function
        local_names: Set[str] = set(_fn_params(fn)) | {"self", "cls", "_"}
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                local_names.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_names.add(node.name)

        tainted = set(params)
        self._propagate_taint(body, tainted)

        for node in ast.walk(fn):
            if isinstance(node, (ast.If, ast.While)):
                if self._expr_taints(node.test, tainted):
                    kind = "if" if isinstance(node, ast.If) else "while"
                    names = sorted(self._tainted_names(node.test, tainted))
                    findings.append(Finding(
                        "GL101", self.relpath, node.lineno,
                        node.col_offset,
                        f"Python `{kind}` on traced value(s) "
                        f"{', '.join(names)} inside traced "
                        f"'{qual}'", symbol=qual))
            elif isinstance(node, ast.Call):
                f = self._impure_call(node)
                if f:
                    findings.append(Finding(
                        "GL102", self.relpath, node.lineno,
                        node.col_offset,
                        f"impure call `{f}` inside traced '{qual}'",
                        symbol=qual))
            elif (isinstance(node, ast.Subscript)
                  and _dotted_name(node.value,
                                   self.aliases) == "os.environ"):
                findings.append(Finding(
                    "GL102", self.relpath, node.lineno, node.col_offset,
                    f"`os.environ[...]` read inside traced '{qual}'",
                    symbol=qual))
            elif isinstance(node, (ast.Global, ast.Nonlocal)):
                kw = ("global" if isinstance(node, ast.Global)
                      else "nonlocal")
                findings.append(Finding(
                    "GL103", self.relpath, node.lineno, node.col_offset,
                    f"`{kw} {', '.join(node.names)}` inside traced "
                    f"'{qual}'", symbol=qual))
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    root = self._root_name(tgt)
                    if root == "self" and not isinstance(tgt, ast.Name):
                        findings.append(Finding(
                            "GL103", self.relpath, node.lineno,
                            node.col_offset,
                            f"write to `self` attribute inside traced "
                            f"'{qual}'", symbol=qual))
                    elif (isinstance(tgt, (ast.Subscript, ast.Attribute))
                          and root is not None
                          and root not in local_names):
                        findings.append(Finding(
                            "GL103", self.relpath, node.lineno,
                            node.col_offset,
                            f"mutation of enclosing-scope `{root}` inside "
                            f"traced '{qual}'", symbol=qual))
        # container-mutation method calls on closure/module names — only
        # when the result is discarded (a bare `x.append(v)` statement);
        # `new, opt = tx.update(...)` is the pure-functional optax idiom
        for node in ast.walk(fn):
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATING_METHODS
                    and isinstance(self.parents.get(node), ast.Expr)):
                root = self._root_name(node.func.value)
                if root is not None and root not in local_names:
                    findings.append(Finding(
                        "GL103", self.relpath, node.lineno,
                        node.col_offset,
                        f"`{root}.{node.func.attr}(...)` mutates "
                        f"enclosing scope inside traced '{qual}'",
                        symbol=qual))
        return findings

    def _propagate_taint(self, body: List[ast.stmt],
                         tainted: Set[str]) -> None:
        """Forward sweeps to fixpoint adding assignment targets whose RHS
        uses a tainted value non-statically. Terminates: the tainted set
        only grows and is bounded by the function's name count."""
        while True:
            before = len(tainted)
            for node in ast.walk(ast.Module(body=body, type_ignores=[])):
                if isinstance(node, (ast.Assign, ast.AnnAssign,
                                     ast.AugAssign)):
                    value = node.value
                    if value is None or not self._expr_taints(value,
                                                              tainted):
                        continue
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for tgt in targets:
                        for n in ast.walk(tgt):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
                elif isinstance(node, ast.For):
                    if self._expr_taints(node.iter, tainted):
                        for n in ast.walk(node.target):
                            if isinstance(n, ast.Name):
                                tainted.add(n.id)
            if len(tainted) == before:
                break

    def _tainted_names(self, expr: ast.AST,
                       tainted: Set[str]) -> Set[str]:
        out: Set[str] = set()
        parents = _build_parents(expr)

        def is_static_usage(name_node: ast.Name) -> bool:
            cur: ast.AST = name_node
            parent = parents.get(cur)
            while parent is not None:
                if isinstance(parent, ast.Attribute) and \
                        parent.attr in _STATIC_ATTRS:
                    return True
                if isinstance(parent, ast.Call):
                    head = _dotted_name(parent.func, self.aliases)
                    if head in _STATIC_FUNCS or (
                            head and head.split(".")[-1] in _STATIC_FUNCS):
                        return True
                if isinstance(parent, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in parent.ops):
                    return True
                cur, parent = parent, parents.get(parent)
            return False

        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and node.id in tainted:
                if not is_static_usage(node):
                    out.add(node.id)
        return out

    def _expr_taints(self, expr: ast.AST, tainted: Set[str]) -> bool:
        return bool(self._tainted_names(expr, tainted))

    def _root_name(self, node: ast.AST) -> Optional[str]:
        cur = node
        while isinstance(cur, (ast.Attribute, ast.Subscript)):
            cur = cur.value
        return cur.id if isinstance(cur, ast.Name) else None

    def _impure_call(self, call: ast.Call) -> Optional[str]:
        head = _dotted_name(call.func, self.aliases)
        if head is None:
            return None
        if head in _PURE_EXEMPT:
            return None
        if head in _IMPURE_BUILTINS or head in _IMPURE_EXACT:
            return head
        for prefix in _IMPURE_PREFIX:
            if head == prefix.rstrip(".") or head.startswith(prefix):
                # `random.` must be the stdlib module, not a local var —
                # _dotted_name only alias-resolves the ROOT name, so check
                # the root really is an import
                root = head.split(".")[0]
                if root in self.aliases.values() or root in (
                        "os", "random", "numpy", "time", "datetime"):
                    if root == "random" and "random" not in self.aliases:
                        return None
                    return head
        return None

    # -- GL105 -------------------------------------------------------------
    def _check_hot_loop_sync(self, tree: ast.Module) -> List[Finding]:
        if not self.relpath.startswith(self._hot_prefixes):
            return []
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            head = _dotted_name(node.func, self.aliases)
            is_sync = head in _SYNC_CALLS or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _SYNC_METHODS)
            if not is_sync:
                continue
            loop = self._enclosing_loop(node)
            if loop is None:
                continue
            fn = self._enclosing_function(node)
            qual = _qualname(fn, self.scopes) if fn is not None else ""
            what = head or node.func.attr  # type: ignore[union-attr]
            findings.append(Finding(
                "GL105", self.relpath, node.lineno, node.col_offset,
                f"blocking host sync `{what}` inside a loop in hot-path "
                f"module (per-iteration device stall)", symbol=qual))
        return findings

    def _enclosing_loop(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.While)):
                return cur
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                # don't escape into an enclosing function's loop: a helper
                # defined inside a loop body runs when called, not per
                # iteration of the def site
                return None
            cur = self.parents.get(cur)
        return None

    def _enclosing_function(self, node: ast.AST) -> Optional[FunctionNode]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None
