"""graftlint: the fleet's contract suite as static analysis.

AST passes purpose-built for this codebase's failure modes:

- trace-safety (GL1xx): jitted step functions must be retrace-safe and
  donation-correct — elastic resharding breaks first at silent
  recompilation/donation bugs.
- lock-discipline (GL2xx): the threaded master/agent components must
  follow a consistent lock discipline or failover races in exactly the
  window a chaos kill opens.
- state-roundtrip (GL3xx): classes in the crash-consistent state
  backend must export/restore every mutable attribute (or annotate it
  ephemeral), with symmetric snapshot keys.
- protocol-symmetry (GL4xx, cross-module): message fields, servicer
  dispatch arms, client wrappers and constants.py contracts must agree
  across common/messages, master/servicer+coord_service and
  agent/master_client.
- hot-path-blocking (GL5xx): no file I/O / sleep / RPC reachable —
  even through helpers — under a gradient-path lock.
- obs-drift (GL6xx, cross-artifact): docs/observability.md catalogs and
  obs/tsdb.DASHBOARD_SERIES must match what the code actually emits,
  both directions.
- graftrace (GL7xx): the fleet's concurrency model as contracts — the
  whole-program thread roster (GL701), the project lock-order graph
  pinned to docs/fault_tolerance.md (GL702, cross-module), fence-gate
  discipline for master state-dir writers (GL703, cross-module) and
  epoch/generation staleness discipline for hot-KV keys and stamped
  plans (GL704).  The runtime half (``lockcheck``) validates the
  static GL702 model under tier-1 via ``tools/graftrace.py``.

Entry points: ``tools/graftlint.py`` (CLI + CI gate),
``run_analysis`` (library), ``tests/test_graftlint.py`` (tier-1 gate).
See docs/static_analysis.md for the rule catalog.
"""

from dlrover_tpu.analysis.concurrency import (    # noqa: F401
    ConcurrencyPass,
    analyze_concurrency,
    build_lock_model,
    check_lock_order,
    find_cycles,
    parse_lock_table,
)
from dlrover_tpu.analysis.contracts import (      # noqa: F401
    StalenessPass,
    check_fence,
    extract_fence_facts,
)
from dlrover_tpu.analysis.findings import (       # noqa: F401
    Finding,
    RULES,
    Rule,
    distinct_rule_ids,
    rules_signature,
)
from dlrover_tpu.analysis.lock_discipline import (  # noqa: F401
    LockDisciplinePass,
)
from dlrover_tpu.analysis.obs_drift import (      # noqa: F401
    check_obs_catalog,
    parse_catalog,
)
from dlrover_tpu.analysis.protocol import (       # noqa: F401
    check_protocol,
    extract_protocol_facts,
)
from dlrover_tpu.analysis.runner import (         # noqa: F401
    AnalysisResult,
    analyze_file,
    load_baseline,
    load_cache,
    run_analysis,
    save_cache,
    write_baseline,
)
from dlrover_tpu.analysis.state_roundtrip import (  # noqa: F401
    StateRoundtripPass,
)
from dlrover_tpu.analysis.trace_safety import (   # noqa: F401
    TraceSafetyPass,
)
