"""graftlint: trace-safety + lock-discipline static analysis.

Two AST passes purpose-built for this codebase's failure modes:

- trace-safety (GL1xx): jitted step functions must be retrace-safe and
  donation-correct — elastic resharding breaks first at silent
  recompilation/donation bugs.
- lock-discipline (GL2xx): the threaded master/agent components must
  follow a consistent lock discipline or failover races in exactly the
  window a chaos kill opens.

Entry points: ``tools/graftlint.py`` (CLI + CI gate),
``run_analysis`` (library), ``tests/test_graftlint.py`` (tier-1 gate).
See docs/static_analysis.md for the rule catalog.
"""

from dlrover_tpu.analysis.findings import (       # noqa: F401
    Finding,
    RULES,
    Rule,
    distinct_rule_ids,
)
from dlrover_tpu.analysis.lock_discipline import (  # noqa: F401
    LockDisciplinePass,
)
from dlrover_tpu.analysis.runner import (         # noqa: F401
    AnalysisResult,
    analyze_file,
    load_baseline,
    run_analysis,
    write_baseline,
)
from dlrover_tpu.analysis.trace_safety import (   # noqa: F401
    TraceSafetyPass,
)
