"""graftlint driver: walk a package, run both passes, apply baseline.

The baseline file (tools/graftlint_baseline.json) holds fingerprints of
accepted pre-existing findings; the gate fails only on findings NOT in the
baseline, so the analyzer can be adopted incrementally without a
flag-day cleanup (and the tier-1 test stays green while still catching
every *new* violation).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dlrover_tpu.analysis.findings import (
    Finding,
    apply_pragmas,
    file_skipped,
    sort_findings,
    source_line,
)
from dlrover_tpu.analysis.lock_discipline import LockDisciplinePass
from dlrover_tpu.analysis.trace_safety import TraceSafetyPass

BASELINE_VERSION = 1


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]              # all post-pragma findings
    new_findings: List[Finding]          # not covered by the baseline
    fingerprints: Dict[str, str]         # fingerprint -> "path:line rule"
    files_analyzed: int = 0
    parse_errors: List[str] = dataclasses.field(default_factory=list)
    analyzed_relpaths: List[str] = dataclasses.field(default_factory=list)


def package_relpath(path: str) -> Optional[str]:
    """Path relative to the TOP enclosing package directory (the nearest
    ancestor chain of __init__.py dirs), or None outside any package.

    Anchoring on the package — not the invocation root — keeps hot-path
    prefixes (``trainer/``) and baseline fingerprints identical whether
    the analyzer is pointed at ``dlrover_tpu``, ``dlrover_tpu/trainer``,
    or a single file."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    top = None
    while os.path.exists(os.path.join(d, "__init__.py")):
        top = d
        d = os.path.dirname(d)
    if top is None:
        return None
    return os.path.relpath(path, top).replace(os.sep, "/")


def iter_python_files(root: str) -> Iterable[Tuple[str, str]]:
    """Yield (abspath, relpath) for package .py files, skipping caches."""
    root = os.path.abspath(root)
    if os.path.isfile(root):
        yield root, package_relpath(root) or os.path.basename(root)
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in ("__pycache__", ".git", "node_modules"))
        for name in sorted(filenames):
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                yield path, package_relpath(path) or os.path.relpath(
                    path, root).replace(os.sep, "/")


def analyze_file(path: str, relpath: str,
                 source: Optional[str] = None) -> List[Finding]:
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    lines = source.splitlines()
    if file_skipped(lines):
        return []
    tree = ast.parse(source, filename=path)
    findings: List[Finding] = []
    findings.extend(TraceSafetyPass().run(relpath, tree, lines))
    findings.extend(LockDisciplinePass().run(relpath, tree, lines))
    return apply_pragmas(findings, lines)


def run_analysis(roots: Sequence[str],
                 baseline: Optional[Dict] = None) -> AnalysisResult:
    pairs: List[Tuple[Finding, str]] = []   # (finding, fingerprint)
    fingerprints: Dict[str, str] = {}
    parse_errors: List[str] = []
    analyzed: List[str] = []
    seen_paths: set = set()
    files = 0
    for root in roots:
        for path, relpath in iter_python_files(root):
            abspath = os.path.abspath(path)
            if abspath in seen_paths:
                continue      # overlapping roots: analyze each file once
            seen_paths.add(abspath)
            files += 1
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                found = analyze_file(path, relpath, source)
            except (SyntaxError, ValueError, UnicodeDecodeError,
                    OSError) as e:
                # SyntaxError from ast.parse; ValueError for NUL bytes;
                # UnicodeDecodeError for non-UTF8 sources; OSError for
                # unreadable files (dangling symlink, permissions). NOT
                # recorded as analyzed: a file that failed to parse must
                # keep its baseline entries (write_baseline drops entries
                # only for successfully re-analyzed files)
                parse_errors.append(f"{relpath}: {e}")
                continue
            analyzed.append(relpath)
            lines = source.splitlines()
            # identical findings on textually identical lines (same rule,
            # symbol, source text) get an occurrence suffix in line order:
            # baselining the first must NOT suppress a second, newly-added
            # copy of the same violation
            found.sort(key=lambda f: (f.line, f.col, f.rule_id))
            occurrence: Dict[str, int] = {}
            for fnd in found:
                base = fnd.fingerprint(source_line(lines, fnd.line))
                n = occurrence.get(base, 0)
                occurrence[base] = n + 1
                fp = base if n == 0 else f"{base}#{n}"
                fingerprints[fp] = f"{fnd.path}:{fnd.line} {fnd.rule_id}"
                pairs.append((fnd, fp))
    suppressed = set((baseline or {}).get("suppressions", []))
    new = [fnd for fnd, fp in pairs if fp not in suppressed]
    return AnalysisResult(
        findings=sort_findings([f for f, _ in pairs]),
        new_findings=sort_findings(new),
        fingerprints=fingerprints,
        files_analyzed=files,
        parse_errors=parse_errors,
        analyzed_relpaths=analyzed,
    )


def load_baseline(path: str) -> Optional[Dict]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')}")
    return data


def write_baseline(path: str, result: AnalysisResult) -> None:
    """Accept the run's findings into the baseline.

    Entries for files ANALYZED in this run are replaced by the run's
    findings (so fixed findings drop out); entries for files outside the
    analyzed roots are preserved — a partial-tree `--write-baseline`
    must not discard the rest of the package's accepted debt."""
    notes: Dict[str, str] = dict(result.fingerprints)
    analyzed = set(result.analyzed_relpaths)
    old = None
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as f:
                old = json.load(f)
        except (ValueError, OSError) as e:
            # refuse rather than silently discard every previously
            # accepted suppression outside the analyzed roots
            raise ValueError(
                f"existing baseline {path} is unreadable ({e}); fix or "
                f"delete it before --write-baseline") from e
    for fp in (old or {}).get("suppressions", []):
        if fp in notes:
            continue
        note = (old or {}).get("notes", {}).get(fp, "")
        note_path = note.split(":", 1)[0]   # note format: "path:line RULE"
        if note_path and note_path in analyzed:
            continue      # re-derived (or fixed) in this run: drop
        notes[fp] = note
    data = {
        "version": BASELINE_VERSION,
        "comment": (
            "accepted pre-existing graftlint findings; regenerate with "
            "`python tools/graftlint.py --write-baseline <roots>` after "
            "reviewing that every entry is a deliberate acceptance"),
        "suppressions": sorted(notes),
        "notes": {fp: where for fp, where in sorted(notes.items())},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
