"""graftlint driver: walk a package, run every pass, apply baseline.

Per-file passes (trace-safety, lock-discipline + hot-path, state-
roundtrip) and per-file FACT extraction (protocol + obs emission sites)
run once per file and are cached; the cross-module checkers (protocol
symmetry, obs-catalog drift) then run over the pooled facts — so a
warm-cache whole-package run re-parses only changed files and stays
fast as the repo grows.

The baseline file (tools/graftlint_baseline.json) holds fingerprints of
accepted pre-existing findings; the gate fails only on findings NOT in
the baseline, so the analyzer can be adopted incrementally without a
flag-day cleanup (and the tier-1 test stays green while still catching
every *new* violation). Fingerprints embed each rule's VERSION, so
bumping a rule's logic invalidates its stale suppressions.

The cache (tools/.graftlint_cache.json) keys each file on
(path, mtime_ns, size) under a global rules-signature: any rule
addition/removal/version bump discards the whole cache.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from dlrover_tpu.analysis.concurrency import (
    analyze_concurrency,
    check_lock_order,
)
from dlrover_tpu.analysis.contracts import (
    StalenessPass,
    check_fence,
    extract_fence_facts,
)
from dlrover_tpu.analysis.findings import (
    Finding,
    apply_pragmas,
    file_skipped,
    line_pragmas,
    rules_signature,
    sort_findings,
    source_line,
)
from dlrover_tpu.analysis.lock_discipline import LockDisciplinePass
from dlrover_tpu.analysis.obs_drift import (
    check_obs_catalog,
    extract_obs_facts,
)
from dlrover_tpu.analysis.protocol import (
    check_protocol,
    extract_protocol_facts,
)
from dlrover_tpu.analysis.state_roundtrip import StateRoundtripPass
from dlrover_tpu.analysis.trace_safety import TraceSafetyPass

BASELINE_VERSION = 2
# 2: concurrency facts grew binds/families and entry-lockset call
# edges — facts cached by v1 would silently miss lock-order edges
# 3: calls facts carry a ctor/call kind tag and module-function lock
# facts (modfuncs) joined the schema
CACHE_VERSION = 3
# a cold run fans misses out over a process pool only past this count:
# below it the fork+import cost exceeds the analysis itself, and the
# deterministic sequential path keeps single-file runs trivially simple
PARALLEL_MIN_FILES = 8


@dataclasses.dataclass
class AnalysisResult:
    findings: List[Finding]              # all post-pragma findings
    new_findings: List[Finding]          # not covered by the baseline
    fingerprints: Dict[str, str]         # fingerprint -> "path:line rule"
    files_analyzed: int = 0
    parse_errors: List[str] = dataclasses.field(default_factory=list)
    analyzed_relpaths: List[str] = dataclasses.field(default_factory=list)
    cache_hits: int = 0
    cache_misses: int = 0
    wall_time_s: float = 0.0


def package_relpath(path: str) -> Optional[str]:
    """Path relative to the TOP enclosing package directory (the nearest
    ancestor chain of __init__.py dirs), or None outside any package.

    Anchoring on the package — not the invocation root — keeps hot-path
    prefixes (``trainer/``) and baseline fingerprints identical whether
    the analyzer is pointed at ``dlrover_tpu``, ``dlrover_tpu/trainer``,
    or a single file."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    top = None
    while os.path.exists(os.path.join(d, "__init__.py")):
        top = d
        d = os.path.dirname(d)
    if top is None:
        return None
    return os.path.relpath(path, top).replace(os.sep, "/")


def iter_python_files(root: str) -> Iterable[Tuple[str, str]]:
    """Yield (abspath, relpath) for package .py files, skipping caches."""
    root = os.path.abspath(root)
    if os.path.isfile(root):
        yield root, package_relpath(root) or os.path.basename(root)
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = sorted(
            d for d in dirnames
            if d not in ("__pycache__", ".git", "node_modules"))
        for name in sorted(filenames):
            if name.endswith(".py"):
                path = os.path.join(dirpath, name)
                yield path, package_relpath(path) or os.path.relpath(
                    path, root).replace(os.sep, "/")


def _analyze_source(path: str, relpath: str,
                    source: str) -> Tuple[List[Finding], Dict, Dict]:
    """One file through every per-file pass + fact extractor. Returns
    (post-pragma findings, cross-module facts, pragma map)."""
    lines = source.splitlines()
    if file_skipped(lines):
        return [], {}, {}
    tree = ast.parse(source, filename=path)
    findings: List[Finding] = []
    findings.extend(TraceSafetyPass().run(relpath, tree, lines))
    findings.extend(LockDisciplinePass().run(relpath, tree, lines))
    findings.extend(StateRoundtripPass().run(relpath, tree, lines))
    findings.extend(StalenessPass().run(relpath, tree, lines))
    conc_findings, conc_facts = analyze_concurrency(relpath, tree, lines)
    findings.extend(conc_findings)
    facts = extract_protocol_facts(relpath, tree, lines)
    obs_facts = extract_obs_facts(relpath, tree, lines)
    if obs_facts:
        facts["obs"] = obs_facts
    if conc_facts:
        facts["conc"] = conc_facts
    fence_facts = extract_fence_facts(relpath, tree, lines)
    if fence_facts:
        facts["fence"] = fence_facts
    pragmas = {str(k): sorted(v)
               for k, v in line_pragmas(lines).items()}
    return apply_pragmas(findings, lines), facts, pragmas


def analyze_file(path: str, relpath: str,
                 source: Optional[str] = None) -> List[Finding]:
    """Single-file entry point (fixture tests): per-file passes only."""
    if source is None:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    findings, _, _ = _analyze_source(path, relpath, source)
    return findings


# -- the per-file cache ------------------------------------------------------

def _finding_to_dict(f: Finding) -> Dict:
    return {"rule_id": f.rule_id, "path": f.path, "line": f.line,
            "col": f.col, "message": f.message, "symbol": f.symbol}


def _finding_from_dict(d: Dict) -> Finding:
    return Finding(rule_id=d["rule_id"], path=d["path"],
                   line=int(d["line"]), col=int(d["col"]),
                   message=d["message"], symbol=d.get("symbol", ""))


def load_cache(path: str) -> Dict:
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if not isinstance(data, dict) or \
            data.get("version") != CACHE_VERSION or \
            data.get("rules") != rules_signature():
        return {}        # rule logic changed: every result is stale
    files = data.get("files")
    return files if isinstance(files, dict) else {}


def save_cache(path: str, files: Dict) -> None:
    data = {"version": CACHE_VERSION, "rules": rules_signature(),
            "files": files}
    tmp = f"{path}.{os.getpid()}.tmp"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(data, f)
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass


def _file_key(path: str) -> Optional[List[int]]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return [st.st_mtime_ns, st.st_size]


# -- the whole-run driver ----------------------------------------------------

def _doc_relpath(doc_path: str) -> str:
    parts = os.path.abspath(doc_path).replace(os.sep, "/").split("/")
    return "/".join(parts[-2:])


def _analyze_one(task: Tuple[str, str]) -> Tuple[str, Optional[Dict],
                                                 Optional[str]]:
    """Pool-safe per-file worker: (abspath, relpath) -> (abspath,
    serialized payload, error). Everything in the payload is
    JSON-shaped so the fork pool can pickle it and the cache can store
    it verbatim."""
    abspath, relpath = task
    try:
        with open(abspath, encoding="utf-8") as f:
            source = f.read()
        findings, facts, pragmas = _analyze_source(
            abspath, relpath, source)
    except (SyntaxError, ValueError, UnicodeDecodeError,
            OSError) as e:
        # SyntaxError from ast.parse; ValueError for NUL bytes;
        # UnicodeDecodeError for non-UTF8 sources; OSError for
        # unreadable files
        return abspath, None, f"{relpath}: {e}"
    lines = source.splitlines()
    payload = {
        "findings": [
            dict(_finding_to_dict(fnd),
                 srcline=source_line(lines, fnd.line))
            for fnd in findings],
        "facts": facts,
        "pragmas": pragmas,
    }
    return abspath, payload, None


def _analyze_many(tasks: List[Tuple[str, str]],
                  jobs: int) -> List[Tuple[str, Optional[Dict],
                                           Optional[str]]]:
    """Run the per-file worker over every miss — through a fork pool
    when the batch is big enough, sequentially otherwise. Results come
    back in task order either way, so cache contents, fingerprints and
    parse-error ordering are identical across both paths."""
    if jobs > 1 and len(tasks) >= PARALLEL_MIN_FILES:
        try:
            import multiprocessing

            ctx = multiprocessing.get_context("fork")
            with ctx.Pool(processes=min(jobs, len(tasks))) as pool:
                return pool.map(_analyze_one, tasks)
        except (ImportError, OSError, ValueError):
            pass          # no fork on this platform: sequential path
    return [_analyze_one(t) for t in tasks]


def run_analysis(roots: Sequence[str],
                 baseline: Optional[Dict] = None,
                 cache_path: Optional[str] = None,
                 obs_doc: Optional[str] = None,
                 lock_doc: Optional[str] = None,
                 jobs: int = 1) -> AnalysisResult:
    started = time.monotonic()
    per_path: Dict[str, List[Tuple[Finding, str]]] = {}
    facts_by_path: Dict[str, Dict] = {}
    pragmas_by_path: Dict[str, Dict[str, List[str]]] = {}
    display_path: Dict[str, str] = {}   # unique fact key -> real relpath
    parse_errors: List[str] = []
    analyzed: List[str] = []
    seen_paths: set = set()
    hits = misses = 0

    cache = load_cache(cache_path) if cache_path else {}
    cache_out: Dict = dict(cache)

    # pass 1: enumerate + cache probe, collecting the miss list so a
    # cold run can fan it out across a process pool (the warm fast
    # path — all hits — never touches the pool)
    entries: List[Tuple[str, str, Optional[List[int]],
                        Optional[Dict]]] = []
    to_analyze: List[Tuple[str, str]] = []
    for root in roots:
        for path, relpath in iter_python_files(root):
            abspath = os.path.abspath(path)
            if abspath in seen_paths:
                continue      # overlapping roots: analyze each file once
            seen_paths.add(abspath)
            key = _file_key(abspath)
            entry = cache.get(abspath)
            if not (entry is not None and key is not None
                    and entry.get("key") == key
                    and entry.get("relpath") == relpath):
                entry = None
                to_analyze.append((abspath, relpath))
            entries.append((abspath, relpath, key, entry))
    files = len(entries)
    fresh: Dict[str, Tuple[Optional[Dict], Optional[str]]] = {
        abspath: (payload, err)
        for abspath, payload, err in _analyze_many(to_analyze, jobs)}

    for abspath, relpath, key, entry in entries:
        if entry is not None:
            hits += 1
            payload = entry
        else:
            misses += 1
            payload, err = fresh[abspath]
            if payload is None:
                # NOT recorded as analyzed: a file that failed to
                # parse must keep its baseline entries
                # (write_baseline drops entries only for re-analyzed
                # files)
                parse_errors.append(err or f"{relpath}: unknown error")
                cache_out.pop(abspath, None)
                continue
            if key is not None:
                cache_out[abspath] = dict(payload, key=key,
                                          relpath=relpath)
        found = [(_finding_from_dict(d), d.get("srcline", ""))
                 for d in payload.get("findings", [])]
        facts = payload.get("facts") or {}
        pragmas = payload.get("pragmas") or {}
        analyzed.append(relpath)
        # distinct files can share a package-relative path when the
        # analyzed roots span several packages (the real package +
        # a fixture package): FACTS keep a unique key so the
        # cross-module checkers never diff a chimera of two
        # unrelated modules, while findings group by the REAL
        # relpath — colliding files share one occurrence-suffix
        # group, so textually identical findings still get
        # distinct fingerprints
        key_path = relpath
        suffix = 2
        while key_path in facts_by_path:
            key_path = f"{relpath}#{suffix}"
            suffix += 1
        display_path[key_path] = relpath
        facts_by_path[key_path] = facts
        pragmas_by_path[key_path] = pragmas
        per_path.setdefault(relpath, []).extend(found)

    # -- cross-module checkers over the pooled facts ---------------------
    cross: List[Tuple[Finding, str]] = list(
        check_protocol(facts_by_path))
    cross.extend(check_fence(facts_by_path))
    lock_doc_rel = lock_doc_text = None
    if lock_doc:
        lock_doc_rel = _doc_relpath(lock_doc)
        try:
            with open(lock_doc, encoding="utf-8") as f:
                lock_doc_text = f.read()
        except OSError as e:
            # same discipline as the obs catalog: a missing hierarchy
            # table must FAIL the run, not silently skip GL702's
            # doc-contract half
            parse_errors.append(f"{lock_doc_rel}: lock-order table "
                                f"unreadable ({e})")
            lock_doc_rel = lock_doc_text = None
        else:
            analyzed.append(lock_doc_rel)
    # cycles are checked with or without the doc contract
    cross.extend(check_lock_order(facts_by_path, lock_doc_rel,
                                  lock_doc_text))
    if obs_doc:
        doc_rel = _doc_relpath(obs_doc)
        try:
            with open(obs_doc, encoding="utf-8") as f:
                doc_text = f.read()
        except OSError as e:
            # a missing/unreadable catalog must FAIL the run, not
            # silently disable GL601/602/603 — same discipline as a
            # file that failed to parse
            parse_errors.append(f"{doc_rel}: obs catalog unreadable "
                                f"({e})")
        else:
            cross.extend(check_obs_catalog(doc_rel, doc_text,
                                           facts_by_path))
            # the doc WAS analyzed this run: write_baseline replaces
            # entries for analyzed paths, so a fixed doc row's stale
            # suppression drops out instead of surviving every
            # regenerate
            analyzed.append(doc_rel)
    for fnd, srcline in cross:
        pragmas = pragmas_by_path.get(fnd.path, {})
        disabled = set(pragmas.get(str(fnd.line), ()))
        if fnd.rule_id in disabled or "ALL" in disabled:
            continue
        # a cross-module finding carries the fact KEY as its path;
        # translate back to the real relpath so reports and
        # fingerprints never cite a phantom "path#2" file
        real = display_path.get(fnd.path, fnd.path)
        if real != fnd.path:
            fnd = dataclasses.replace(fnd, path=real)
        per_path.setdefault(real, []).append((fnd, srcline))

    # -- fingerprints (occurrence-suffixed per file) ---------------------
    pairs: List[Tuple[Finding, str]] = []   # (finding, fingerprint)
    fingerprints: Dict[str, str] = {}
    for relpath in sorted(per_path):
        found = per_path[relpath]
        # identical findings on textually identical lines (same rule,
        # symbol, source text) get an occurrence suffix in line order:
        # baselining the first must NOT suppress a second, newly-added
        # copy of the same violation
        found.sort(key=lambda pair: (pair[0].line, pair[0].col,
                                     pair[0].rule_id))
        occurrence: Dict[str, int] = {}
        for fnd, srcline in found:
            base = fnd.fingerprint(srcline)
            n = occurrence.get(base, 0)
            occurrence[base] = n + 1
            fp = base if n == 0 else f"{base}#{n}"
            fingerprints[fp] = f"{fnd.path}:{fnd.line} {fnd.rule_id}"
            pairs.append((fnd, fp))

    if cache_path:
        # prune entries for files that are gone (deleted/renamed):
        # without this the cache would grow unboundedly with dead
        # findings/facts payloads. A prune counts as a change worth
        # persisting even on an otherwise all-hit run.
        pruned = 0
        for stale in list(cache_out):
            if stale not in seen_paths and not os.path.exists(stale):
                del cache_out[stale]
                pruned += 1
        if misses or pruned:
            save_cache(cache_path, cache_out)

    suppressed = set((baseline or {}).get("suppressions", []))
    new = [fnd for fnd, fp in pairs if fp not in suppressed]
    return AnalysisResult(
        findings=sort_findings([f for f, _ in pairs]),
        new_findings=sort_findings(new),
        fingerprints=fingerprints,
        files_analyzed=files,
        parse_errors=parse_errors,
        analyzed_relpaths=analyzed,
        cache_hits=hits,
        cache_misses=misses,
        wall_time_s=time.monotonic() - started,
    )


def load_baseline(path: str) -> Optional[Dict]:
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path}: unsupported version {data.get('version')}")
    return data


def write_baseline(path: str, result: AnalysisResult) -> None:
    """Accept the run's findings into the baseline.

    Entries for files ANALYZED in this run are replaced by the run's
    findings (so fixed findings drop out); entries for files outside the
    analyzed roots are preserved — a partial-tree `--write-baseline`
    must not discard the rest of the package's accepted debt."""
    notes: Dict[str, str] = dict(result.fingerprints)
    analyzed = set(result.analyzed_relpaths)
    old = None
    if os.path.exists(path):
        try:
            with open(path, encoding="utf-8") as f:
                old = json.load(f)
        except (ValueError, OSError) as e:
            # refuse rather than silently discard every previously
            # accepted suppression outside the analyzed roots
            raise ValueError(
                f"existing baseline {path} is unreadable ({e}); fix or "
                f"delete it before --write-baseline") from e
    for fp in (old or {}).get("suppressions", []):
        if fp in notes:
            continue
        note = (old or {}).get("notes", {}).get(fp, "")
        note_path = note.split(":", 1)[0]   # note format: "path:line RULE"
        if note_path and note_path in analyzed:
            continue      # re-derived (or fixed) in this run: drop
        notes[fp] = note
    data = {
        "version": BASELINE_VERSION,
        "rules": rules_signature(),
        "comment": (
            "accepted pre-existing graftlint findings; regenerate with "
            "`python tools/graftlint.py --write-baseline <roots>` after "
            "reviewing that every entry is a deliberate acceptance"),
        "suppressions": sorted(notes),
        "notes": {fp: where for fp, where in sorted(notes.items())},
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")
