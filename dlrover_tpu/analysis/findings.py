"""Finding/Rule model shared by the graftlint passes.

A Finding carries (rule_id, file:line, message, symbol) plus a *stable
fingerprint* — a hash of everything EXCEPT the line number, so a checked-in
baseline (tools/graftlint_baseline.json) keeps suppressing a pre-existing
violation while unrelated edits shift it around the file. Inline
suppression follows the pylint convention::

    risky_call()  # graftlint: disable=GL102

and ``# graftlint: skip-file`` anywhere in the first 5 lines exempts a
module (for generated or vendored code; the test fixtures do NOT use it —
their deliberate violations must stay visible to the fixture tests).
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    pass_name: str            # "trace-safety" | "lock-discipline" |
    #                           "state-roundtrip" | "protocol-symmetry" |
    #                           "hot-path-blocking" | "obs-drift"
    title: str
    hint: str
    version: int = 1          # bump when the rule's LOGIC changes: the
    #                           version is part of every fingerprint, so
    #                           stale baseline suppressions written
    #                           against the old logic stop matching
    #                           instead of silently masking new findings


RULES: Dict[str, Rule] = {
    r.rule_id: r
    for r in [
        Rule(
            "GL101", "trace-safety",
            "Python control flow on a traced value",
            "branching on a tracer raises TracerBoolConversionError or "
            "forces a retrace per value; use jax.lax.cond/select/while_loop "
            "or mark the argument static (static_argnums).",
        ),
        Rule(
            "GL102", "trace-safety",
            "impure call inside traced code",
            "time/np.random/os.environ/print run ONCE at trace time and "
            "bake a constant into the compiled program (silently stale "
            "after elastic re-lowering); thread jax.random keys, pass "
            "clocks as arguments, use jax.debug.print.",
        ),
        Rule(
            "GL103", "trace-safety",
            "mutation of enclosing state inside traced code",
            "writes to globals/closures/self from a traced function happen "
            "at trace time, not per step — they replay on every retrace "
            "and never on cached executions; return the value instead.",
        ),
        Rule(
            "GL104", "trace-safety",
            "state-threading jit without buffer donation",
            "a step that takes state and returns updated state holds BOTH "
            "copies in HBM without donate_argnums; pass "
            "donate_argnums/donate_argnames IF callers rebind the "
            "returned state (they must not reuse the donated input); "
            "otherwise suppress with `# graftlint: disable=GL104`.",
        ),
        Rule(
            "GL105", "trace-safety",
            "blocking host sync inside the training hot loop",
            "device_get/block_until_ready inside the step loop stalls the "
            "XLA dispatch pipeline every iteration; sync outside the loop "
            "or on an interval.",
        ),
        Rule(
            "GL201", "lock-discipline",
            "unguarded access to a lock-protected attribute",
            "this attribute is accessed under the class lock almost "
            "everywhere else; take the lock here too (a race in exactly "
            "the window a failover opens).",
        ),
        Rule(
            "GL202", "lock-discipline",
            "inconsistent lock acquisition order",
            "two locks are nested in both orders; pick one global order "
            "(or merge the critical sections) to rule out deadlock.",
        ),
        Rule(
            "GL203", "lock-discipline",
            "blocking call while holding a lock",
            "sleep/subprocess/network inside a critical section stalls "
            "every thread contending for the lock (agents block on master "
            "RPCs exactly during failover); move the slow call outside.",
        ),
        Rule(
            "GL204", "lock-discipline",
            "bare lock acquire() outside a with-statement",
            "a no-argument acquire() leaks the lock on any exception "
            "path; use `with lock:` (timed/non-blocking acquires with "
            "arguments are exempt — pair those with try/finally).",
        ),
        Rule(
            "GL205", "lock-discipline",
            "multi-writer attribute never guarded in a lock-owning class",
            "several methods of a class that owns a lock write this "
            "attribute, but no access ever holds a lock — either guard it "
            "or document why it is single-threaded.",
        ),
        Rule(
            "GL301", "state-roundtrip",
            "mutable state attribute outside the export/restore pair",
            "this class participates in the crash-consistent state "
            "backend, but the attribute is neither touched by "
            "export_state/restore_state (or _export_extra/"
            "_restore_extra) nor annotated `# graftlint: "
            "ephemeral(reason)` — a master failover silently loses it "
            "(the PR 9 `_known_chips` class of bug).",
        ),
        Rule(
            "GL302", "state-roundtrip",
            "asymmetric export/restore key",
            "a key one side of the snapshot roundtrip uses and the "
            "other never mentions restores as a silently-empty default "
            "after failover (or exports dead weight); make restore "
            "consume every key export emits, and vice versa.",
        ),
        Rule(
            "GL401", "protocol-symmetry",
            "message field read on one side but never set on the other",
            "the reader only ever sees the dataclass default — the "
            "'sender predates the field' path, permanently; set the "
            "field at the construction site (or delete it).",
        ),
        Rule(
            "GL402", "protocol-symmetry",
            "RPC endpoint without a client wrapper (or vice versa)",
            "a request type dispatched by the servicer needs a "
            "MasterClient wrapper (and a client-sent type needs a "
            "dispatch arm), or one side of the protocol is "
            "unreachable/unanswerable.",
        ),
        Rule(
            "GL403", "protocol-symmetry",
            "string literal shadows a constants.py contract",
            "KV prefixes, env-var names and rendezvous names are "
            "single-sourced in common/constants.py — a literal copy "
            "drifts the moment the contract changes on one side only "
            "(the HOT_KV_PREFIXES lesson from PR 10); import the "
            "constant.",
        ),
        Rule(
            "GL501", "hot-path-blocking",
            "blocking operation reachable under a gradient-path lock",
            "file I/O, sleeps, RPCs or subprocesses while a hot lock "
            "(KV store condition, mutation log, dcn sync, step "
            "timeline) is held — lexically or via a helper called with "
            "the lock held — put storage/network latency in the "
            "per-step path; move the slow call outside the critical "
            "section (the PR 10 mutation-log lesson).",
        ),
        Rule(
            "GL701", "thread-roster",
            "cross-thread access without a common lock",
            "the thread roster (Thread/Timer/executor targets + RPC "
            "servicer entry points) reaches this attribute from more "
            "than one thread context and no lock is common to all its "
            "accesses — guard every access with one lock, publish via "
            "a threading.Event, or assign only before the thread "
            "starts.",
        ),
        Rule(
            "GL702", "lock-order",
            "lock-order cycle or hierarchy-table drift",
            "the project-wide acquired-while-held graph (lexical "
            "nesting + lock-held helpers + calls into other lock "
            "owners) must stay acyclic AND match the canonical table "
            "in docs/fault_tolerance.md — break the cycle or update "
            "the table (tools/graftrace.py --markdown regenerates the "
            "rows).",
        ),
        Rule(
            "GL703", "fence-discipline",
            "master state-dir writer bypasses the fence gate",
            "every writer under the master state dir must consult the "
            "fence gate on its write path (`self.gate`/`gate` "
            "callable, PR 10's `_check_fenced`), and every "
            "construction site must wire the gate — a deposed master "
            "that keeps writing corrupts the promoted master's state.",
        ),
        Rule(
            "GL704", "staleness-discipline",
            "hot-KV key or stamped plan consumed without its token",
            "hot-prefix KV keys (dcn/, coord/) must embed an epoch/"
            "round/generation segment (or be built by a helper that "
            "namespaces them), and a parsed plan payload must be "
            "validated against its epoch/generation stamp before "
            "commit — a stale payload from the previous world silently "
            "corrupts the new one.",
        ),
        Rule(
            "GL601", "obs-drift",
            "documented observability name not emitted by code",
            "docs/observability.md catalogs a metric/span/flight-event "
            "that nothing registers or emits — either the code lost it "
            "or the docs invented it; reconcile.",
        ),
        Rule(
            "GL602", "obs-drift",
            "emitted observability name missing from the catalog",
            "a metric/span/flight-event the code emits has no row in "
            "docs/observability.md — operators can't discover it and "
            "the next rename drifts silently; add the catalog row.",
        ),
        Rule(
            "GL603", "obs-drift",
            "DASHBOARD_SERIES entry not backed by an emitted series",
            "tools/top.py and the flight snapshot query this name but "
            "nothing ingests or registers it — the dashboard column "
            "renders empty forever; fix the name or the feed.",
        ),
    ]
}


def rules_signature() -> str:
    """Stable digest over (rule_id, version) pairs — the cache and
    baseline invalidation key: any rule addition/removal/version bump
    re-analyzes everything."""
    raw = ";".join(f"{rid}:{RULES[rid].version}" for rid in sorted(RULES))
    return hashlib.sha1(raw.encode()).hexdigest()[:12]


@dataclasses.dataclass
class Finding:
    rule_id: str
    path: str                 # package-relative posix path
    line: int
    col: int
    message: str
    symbol: str = ""          # enclosing function/class qualname

    @property
    def rule(self) -> Rule:
        return RULES[self.rule_id]

    def fingerprint(self, source_line: str = "") -> str:
        norm = re.sub(r"\s+", " ", source_line.strip())
        # the rule VERSION is part of the hash: bumping a rule's logic
        # invalidates that rule's baseline suppressions instead of
        # letting stale entries mask findings the new logic surfaces
        raw = (f"{self.rule_id}v{self.rule.version}"
               f"|{self.path}|{self.symbol}|{norm}")
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule_id} "
                f"[{self.rule.pass_name}] {self.message}\n"
                f"    hint: {self.rule.hint}")


_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9, ]+)")
_SKIP_FILE_RE = re.compile(r"#\s*graftlint:\s*skip-file")
# `self._scratch = {}  # graftlint: ephemeral(rebuilt on restore)` —
# the state-roundtrip pass's opt-out: the attribute is DELIBERATELY
# not part of the snapshot, and the reason is recorded in-line. A bare
# `ephemeral` with no reason does not count: the why is the contract.
_EPHEMERAL_RE = re.compile(r"#\s*graftlint:\s*ephemeral\(([^)]+)\)")


def ephemeral_lines(source_lines: Sequence[str]) -> Dict[int, str]:
    """1-based line -> ephemeral reason for annotated lines."""
    out: Dict[int, str] = {}
    for i, ln in enumerate(source_lines, start=1):
        m = _EPHEMERAL_RE.search(ln)
        if m and m.group(1).strip():
            out[i] = m.group(1).strip()
    return out


def file_skipped(source_lines: Sequence[str]) -> bool:
    return any(_SKIP_FILE_RE.search(ln) for ln in source_lines[:5])


def line_pragmas(source_lines: Sequence[str]) -> Dict[int, set]:
    """1-based line -> set of rule ids disabled on that line."""
    out: Dict[int, set] = {}
    for i, ln in enumerate(source_lines, start=1):
        m = _PRAGMA_RE.search(ln)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def apply_pragmas(findings: List[Finding],
                  source_lines: Sequence[str]) -> List[Finding]:
    pragmas = line_pragmas(source_lines)
    kept = []
    for f in findings:
        disabled = pragmas.get(f.line, set())
        if f.rule_id in disabled or "ALL" in disabled:
            continue
        kept.append(f)
    return kept


def source_line(source_lines: Sequence[str], line: int) -> str:
    if 1 <= line <= len(source_lines):
        return source_lines[line - 1]
    return ""


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule_id))


def distinct_rule_ids(findings: Optional[List[Finding]] = None) -> List[str]:
    if findings is None:
        return sorted(RULES)
    return sorted({f.rule_id for f in findings})
