"""Finding/Rule model shared by the graftlint passes.

A Finding carries (rule_id, file:line, message, symbol) plus a *stable
fingerprint* — a hash of everything EXCEPT the line number, so a checked-in
baseline (tools/graftlint_baseline.json) keeps suppressing a pre-existing
violation while unrelated edits shift it around the file. Inline
suppression follows the pylint convention::

    risky_call()  # graftlint: disable=GL102

and ``# graftlint: skip-file`` anywhere in the first 5 lines exempts a
module (for generated or vendored code; the test fixtures do NOT use it —
their deliberate violations must stay visible to the fixture tests).
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Rule:
    rule_id: str
    pass_name: str            # "trace-safety" | "lock-discipline"
    title: str
    hint: str


RULES: Dict[str, Rule] = {
    r.rule_id: r
    for r in [
        Rule(
            "GL101", "trace-safety",
            "Python control flow on a traced value",
            "branching on a tracer raises TracerBoolConversionError or "
            "forces a retrace per value; use jax.lax.cond/select/while_loop "
            "or mark the argument static (static_argnums).",
        ),
        Rule(
            "GL102", "trace-safety",
            "impure call inside traced code",
            "time/np.random/os.environ/print run ONCE at trace time and "
            "bake a constant into the compiled program (silently stale "
            "after elastic re-lowering); thread jax.random keys, pass "
            "clocks as arguments, use jax.debug.print.",
        ),
        Rule(
            "GL103", "trace-safety",
            "mutation of enclosing state inside traced code",
            "writes to globals/closures/self from a traced function happen "
            "at trace time, not per step — they replay on every retrace "
            "and never on cached executions; return the value instead.",
        ),
        Rule(
            "GL104", "trace-safety",
            "state-threading jit without buffer donation",
            "a step that takes state and returns updated state holds BOTH "
            "copies in HBM without donate_argnums; pass "
            "donate_argnums/donate_argnames IF callers rebind the "
            "returned state (they must not reuse the donated input); "
            "otherwise suppress with `# graftlint: disable=GL104`.",
        ),
        Rule(
            "GL105", "trace-safety",
            "blocking host sync inside the training hot loop",
            "device_get/block_until_ready inside the step loop stalls the "
            "XLA dispatch pipeline every iteration; sync outside the loop "
            "or on an interval.",
        ),
        Rule(
            "GL201", "lock-discipline",
            "unguarded access to a lock-protected attribute",
            "this attribute is accessed under the class lock almost "
            "everywhere else; take the lock here too (a race in exactly "
            "the window a failover opens).",
        ),
        Rule(
            "GL202", "lock-discipline",
            "inconsistent lock acquisition order",
            "two locks are nested in both orders; pick one global order "
            "(or merge the critical sections) to rule out deadlock.",
        ),
        Rule(
            "GL203", "lock-discipline",
            "blocking call while holding a lock",
            "sleep/subprocess/network inside a critical section stalls "
            "every thread contending for the lock (agents block on master "
            "RPCs exactly during failover); move the slow call outside.",
        ),
        Rule(
            "GL204", "lock-discipline",
            "bare lock acquire() outside a with-statement",
            "a no-argument acquire() leaks the lock on any exception "
            "path; use `with lock:` (timed/non-blocking acquires with "
            "arguments are exempt — pair those with try/finally).",
        ),
        Rule(
            "GL205", "lock-discipline",
            "multi-writer attribute never guarded in a lock-owning class",
            "several methods of a class that owns a lock write this "
            "attribute, but no access ever holds a lock — either guard it "
            "or document why it is single-threaded.",
        ),
    ]
}


@dataclasses.dataclass
class Finding:
    rule_id: str
    path: str                 # package-relative posix path
    line: int
    col: int
    message: str
    symbol: str = ""          # enclosing function/class qualname

    @property
    def rule(self) -> Rule:
        return RULES[self.rule_id]

    def fingerprint(self, source_line: str = "") -> str:
        norm = re.sub(r"\s+", " ", source_line.strip())
        raw = f"{self.rule_id}|{self.path}|{self.symbol}|{norm}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule_id} "
                f"[{self.rule.pass_name}] {self.message}\n"
                f"    hint: {self.rule.hint}")


_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*disable=([A-Z0-9, ]+)")
_SKIP_FILE_RE = re.compile(r"#\s*graftlint:\s*skip-file")


def file_skipped(source_lines: Sequence[str]) -> bool:
    return any(_SKIP_FILE_RE.search(ln) for ln in source_lines[:5])


def line_pragmas(source_lines: Sequence[str]) -> Dict[int, set]:
    """1-based line -> set of rule ids disabled on that line."""
    out: Dict[int, set] = {}
    for i, ln in enumerate(source_lines, start=1):
        m = _PRAGMA_RE.search(ln)
        if m:
            out[i] = {r.strip() for r in m.group(1).split(",") if r.strip()}
    return out


def apply_pragmas(findings: List[Finding],
                  source_lines: Sequence[str]) -> List[Finding]:
    pragmas = line_pragmas(source_lines)
    kept = []
    for f in findings:
        disabled = pragmas.get(f.line, set())
        if f.rule_id in disabled or "ALL" in disabled:
            continue
        kept.append(f)
    return kept


def source_line(source_lines: Sequence[str], line: int) -> str:
    if 1 <= line <= len(source_lines):
        return source_lines[line - 1]
    return ""


def sort_findings(findings: List[Finding]) -> List[Finding]:
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule_id))


def distinct_rule_ids(findings: Optional[List[Finding]] = None) -> List[str]:
    if findings is None:
        return sorted(RULES)
    return sorted({f.rule_id for f in findings})
