"""Ray platform backend.

Capability parity: dlrover/python/scheduler/ray.py (RayClient :51,
RayElasticJob :147, RayJobArgs :171) + the ray client/worker
(dlrover/client/platform/ray/ray_job_submitter.py, trainer/worker/
tf_ray_worker.py). Nodes are Ray actors that run the elastic agent; the
master talks to them through the same watcher/scaler interfaces as pods.
Ray itself is an optional dependency — without it, construction raises a
clear error (this image ships no ray; the surface exists for parity and
for deployments that add it).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from dlrover_tpu.common.constants import NodeStatus
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.scheduler.job import JobArgs


def _require_ray():
    try:
        import ray  # noqa: F401

        return ray
    except ImportError as e:
        raise RuntimeError(
            "the ray platform needs the `ray` package (not shipped in "
            "this image); install it or use platform='local'/'k8s'"
        ) from e


class RayActorHandle:
    """One elastic-agent actor (reference: TFRayWorker as an actor)."""

    def __init__(self, actor: Any, node_type: str, node_id: int,
                 rank_index: int):
        self.actor = actor
        self.node_type = node_type
        self.node_id = node_id
        self.rank_index = rank_index
        self.name = f"{node_type}-{node_id}"


class RayClient:
    """Create/destroy agent actors (reference: RayClient,
    scheduler/ray.py:51)."""

    def __init__(self, job_name: str, address: str = "auto"):
        self._ray = _require_ray()
        if not self._ray.is_initialized():
            self._ray.init(address=address, ignore_reinit_error=True)
        self.job_name = job_name
        self._actors: Dict[str, RayActorHandle] = {}

    def create_agent_actor(self, node_type: str, node_id: int,
                           rank_index: int, master_addr: str,
                           entrypoint: List[str],
                           num_cpus: float = 1.0,
                           resources: Optional[dict] = None
                           ) -> RayActorHandle:
        ray = self._ray

        @ray.remote(num_cpus=num_cpus, resources=resources or {})
        class AgentActor:
            def run(self, master_addr, node_id, entrypoint):
                from dlrover_tpu.agent.elastic_agent import (
                    ElasticAgent,
                    WorkerSpec,
                )
                from dlrover_tpu.agent.master_client import MasterClient

                client = MasterClient(master_addr, node_id=node_id,
                                      node_type=node_type)
                agent = ElasticAgent(client,
                                     WorkerSpec(entrypoint=entrypoint))
                return agent.run()

        actor = AgentActor.remote()
        handle = RayActorHandle(actor, node_type, node_id, rank_index)
        handle.future = actor.run.remote(master_addr, node_id, entrypoint)
        self._actors[handle.name] = handle
        logger.info("created ray agent actor %s", handle.name)
        return handle

    def delete_actor(self, name: str) -> bool:
        handle = self._actors.pop(name, None)
        if handle is None:
            return False
        self._ray.kill(handle.actor)
        return True

    def list_actors(self) -> List[RayActorHandle]:
        return list(self._actors.values())

    def actor_status(self, name: str) -> str:
        handle = self._actors.get(name)
        if handle is None:
            return NodeStatus.DELETED
        ready, _ = self._ray.wait([handle.future], timeout=0)
        if not ready:
            return NodeStatus.RUNNING
        try:
            code = self._ray.get(ready[0])
            return (NodeStatus.SUCCEEDED if code == 0
                    else NodeStatus.FAILED)
        except Exception:  # noqa: BLE001 - actor died
            return NodeStatus.FAILED


class RayJobArgs(JobArgs):
    """JobArgs parsed for the ray platform (reference: RayJobArgs :171)."""

    @classmethod
    def from_spec(cls, spec, job_name: str = "", namespace: str = "default",
                  platform: str = "ray"):
        return super().from_spec(spec, job_name=job_name,
                                 namespace=namespace, platform=platform)
