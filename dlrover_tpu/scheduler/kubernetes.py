"""Kubernetes platform client — zero-dependency REST against the API server.

Capability parity: dlrover/python/scheduler/kubernetes.py (k8sClient :85,
K8sElasticJob :327) without the `kubernetes` SDK (not in the image): a thin
HTTPS client over the in-cluster service-account contract
(/var/run/secrets/kubernetes.io/serviceaccount) with create/delete/list/watch
on pods and services, plus the TPU pod-manifest builder. The manifest/
watch-parsing logic is pure and unit-testable; network calls only happen on
a real cluster.
"""

from __future__ import annotations

import json
import os
import ssl
import urllib.error
import urllib.request
from typing import Any, Dict, Iterator, List, Optional

from dlrover_tpu.common.constants import (
    NodeEnv,
    NodeExitReason,
    NodeStatus,
    WorkerExit,
)
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.common.node import NodeResource

_SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"

# k8s pod phase → framework node status (reference: the reference maps the
# same five phases in master/watcher/k8s_watcher.py).
POD_PHASE_TO_STATUS = {
    "Pending": NodeStatus.PENDING,
    "Running": NodeStatus.RUNNING,
    "Succeeded": NodeStatus.SUCCEEDED,
    "Failed": NodeStatus.FAILED,
    "Unknown": NodeStatus.UNKNOWN,
}


def in_cluster() -> bool:
    return os.path.exists(os.path.join(_SA_DIR, "token"))


class K8sApi:
    """Minimal typed REST surface; swap out in tests."""

    def __init__(self, host: Optional[str] = None,
                 token: Optional[str] = None,
                 ca_file: Optional[str] = None):
        self._host = host or "https://{}:{}".format(
            os.environ.get("KUBERNETES_SERVICE_HOST", "kubernetes.default"),
            os.environ.get("KUBERNETES_SERVICE_PORT", "443"),
        )
        if token is None and in_cluster():
            with open(os.path.join(_SA_DIR, "token")) as f:
                token = f.read().strip()
        self._token = token
        ca = ca_file or os.path.join(_SA_DIR, "ca.crt")
        self._ssl = ssl.create_default_context(
            cafile=ca if os.path.exists(ca) else None)
        if not os.path.exists(ca):
            self._ssl.check_hostname = False
            self._ssl.verify_mode = ssl.CERT_NONE

    def request(self, method: str, path: str,
                body: Optional[Dict[str, Any]] = None,
                timeout: float = 30.0) -> Dict[str, Any]:
        req = urllib.request.Request(
            self._host + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
        )
        req.add_header("Accept", "application/json")
        # k8s rejects PATCH with a plain JSON media type (415); it requires
        # one of the patch content types (we use merge-patch).
        if method == "PATCH":
            req.add_header("Content-Type", "application/merge-patch+json")
        else:
            req.add_header("Content-Type", "application/json")
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=self._ssl) as resp:
            return json.loads(resp.read() or b"{}")

    def stream(self, path: str, timeout: float = 3600.0
               ) -> Iterator[Dict[str, Any]]:
        """Line-delimited watch stream."""
        req = urllib.request.Request(self._host + path)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=self._ssl) as resp:
            for line in resp:
                line = line.strip()
                if line:
                    yield json.loads(line)


class K8sClient:
    """Pod/service CRUD in one namespace (reference: k8sClient,
    scheduler/kubernetes.py:85-326)."""

    def __init__(self, namespace: str = "default",
                 api: Optional[K8sApi] = None):
        self.namespace = namespace
        self.api = api or K8sApi()

    # -- pods ----------------------------------------------------------
    def create_pod(self, manifest: Dict[str, Any]) -> bool:
        try:
            self.api.request(
                "POST", f"/api/v1/namespaces/{self.namespace}/pods", manifest)
            return True
        except urllib.error.HTTPError as e:
            logger.error("create_pod failed: %s %s", e.code, e.reason)
            return False

    def delete_pod(self, name: str) -> bool:
        try:
            self.api.request(
                "DELETE", f"/api/v1/namespaces/{self.namespace}/pods/{name}")
            return True
        except urllib.error.HTTPError as e:
            if e.code == 404:
                return True
            logger.error("delete_pod failed: %s %s", e.code, e.reason)
            return False

    def list_pods(self, label_selector: str = "") -> List[Dict[str, Any]]:
        path = f"/api/v1/namespaces/{self.namespace}/pods"
        if label_selector:
            path += f"?labelSelector={label_selector}"
        return self.api.request("GET", path).get("items", [])

    def watch_pods(self, label_selector: str = "",
                   resource_version: str = "") -> Iterator[Dict[str, Any]]:
        path = (f"/api/v1/namespaces/{self.namespace}/pods"
                f"?watch=true&labelSelector={label_selector}")
        if resource_version:
            path += f"&resourceVersion={resource_version}"
        return self.api.stream(path)

    def create_service(self, manifest: Dict[str, Any]) -> bool:
        try:
            self.api.request(
                "POST", f"/api/v1/namespaces/{self.namespace}/services",
                manifest)
            return True
        except urllib.error.HTTPError as e:
            logger.error("create_service failed: %s %s", e.code, e.reason)
            return False

    def patch_custom_resource(self, group: str, version: str, plural: str,
                              name: str, body: Dict[str, Any]) -> bool:
        """Patch a CR (scale-plan relay; reference: elasticjob_scaler.py)."""
        path = (f"/apis/{group}/{version}/namespaces/{self.namespace}"
                f"/{plural}/{name}")
        try:
            self.api.request("PATCH", path, body)
            return True
        except urllib.error.HTTPError as e:
            logger.error("patch CR failed: %s %s", e.code, e.reason)
            return False


# ---------------------------------------------------------------------------
# Pure manifest construction (unit-testable without a cluster).
# ---------------------------------------------------------------------------

def resource_to_limits(resource: NodeResource) -> Dict[str, str]:
    """NodeResource → k8s resource limits (single source of truth, shared
    with the CRD serialization in operator/crd.py)."""
    limits: Dict[str, str] = {}
    if resource.cpu:
        limits["cpu"] = str(resource.cpu)
    if resource.memory_mb:
        limits["memory"] = f"{int(resource.memory_mb)}Mi"
    if resource.chips:
        limits["google.com/tpu"] = str(resource.chips)
    return limits


def tpu_node_selector(chip_type: str, tpu_topology: str = ""
                      ) -> Dict[str, str]:
    """GKE TPU placement labels (single source of truth)."""
    selector: Dict[str, str] = {}
    if chip_type:
        selector["cloud.google.com/gke-tpu-accelerator"] = chip_type
    if tpu_topology:
        selector["cloud.google.com/gke-tpu-topology"] = tpu_topology
    return selector


def shell_command(command: str) -> Optional[List[str]]:
    return ["/bin/sh", "-c", command] if command else None


def build_pod_manifest(
    job_name: str,
    node_type: str,
    node_id: int,
    rank_index: int,
    image: str,
    command: str,
    master_addr: str,
    node_num: int,
    resource: Optional[NodeResource] = None,
    tpu_topology: str = "",
    labels: Optional[Dict[str, str]] = None,
    owner_ref: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """A TPU worker pod with the framework env contract. TPU chips are
    requested via the `google.com/tpu` device-plugin resource and the slice
    topology via the GKE nodeSelector (reference analog: _create_pod,
    master/scaler/pod_scaler.py:352 builds GPU pods with TF_CONFIG)."""
    resource = resource or NodeResource()
    name = f"{job_name}-{node_type}-{node_id}"
    env = [
        {"name": NodeEnv.MASTER_ADDR, "value": master_addr},
        {"name": NodeEnv.NODE_ID, "value": str(node_id)},
        {"name": NodeEnv.NODE_TYPE, "value": node_type},
        {"name": NodeEnv.NODE_RANK, "value": str(rank_index)},
        {"name": NodeEnv.NODE_NUM, "value": str(node_num)},
        {"name": NodeEnv.JOB_NAME, "value": job_name},
    ]
    from dlrover_tpu.common.config import Context

    watchdog_s = Context.singleton().hang_watchdog_s
    if watchdog_s > 0:
        # ship the watchdog knob into the pod: the worker enables the
        # watchdog, and pod_to_fields can classify a SIGABRT exit (134)
        # from the pod spec instead of guessing from master-side config
        env.append({"name": _HANG_WATCHDOG_ENV,
                    "value": str(watchdog_s)})
    limits = resource_to_limits(resource)
    node_selector = tpu_node_selector(resource.chip_type, tpu_topology)
    manifest: Dict[str, Any] = {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": name,
            "labels": dict(labels or {}, **{
                "dlrover-tpu/job": job_name,
                "dlrover-tpu/type": node_type,
                "dlrover-tpu/rank": str(rank_index),
                "dlrover-tpu/node-id": str(node_id),
            }),
        },
        "spec": {
            "restartPolicy": "Never",
            "containers": [{
                "name": "main",
                "image": image,
                "command": shell_command(command),
                "env": env,
                "resources": {"limits": limits, "requests": dict(limits)},
                "ports": [{"containerPort": 8471}],  # TPU runtime port
            }],
            "nodeSelector": node_selector or None,
        },
    }
    if owner_ref:
        manifest["metadata"]["ownerReferences"] = [owner_ref]
    container = manifest["spec"]["containers"][0]
    manifest["spec"] = {k: v for k, v in manifest["spec"].items()
                        if v is not None}
    manifest["spec"]["containers"] = [
        {k: v for k, v in container.items() if v is not None}]
    return manifest


# the Context env-override name for hang_watchdog_s (common/config.py
# derives DLROVER_TPU_<FIELD_UPPER>): build_pod_manifest ships it into
# worker pods, pod_to_fields reads it back for exit classification
_HANG_WATCHDOG_ENV = "DLROVER_TPU_HANG_WATCHDOG_S"


def _pod_hang_enabled(pod: Dict[str, Any]) -> bool:
    """Whether THIS pod ran with the step-hang watchdog on — from the
    pod's own spec env when present (the worker knob is set per pod,
    not on the master), falling back to the master's Context."""
    for container in pod.get("spec", {}).get("containers", []):
        for entry in container.get("env", []) or []:
            if entry.get("name") == _HANG_WATCHDOG_ENV:
                try:
                    return float(entry.get("value", "0") or "0") > 0
                except ValueError:
                    return False
    from dlrover_tpu.common.config import Context

    return Context.singleton().hang_watchdog_s > 0


def pod_to_fields(pod: Dict[str, Any]) -> Dict[str, Any]:
    """Parse a pod object into the watcher's neutral fields (reference:
    PodWatcher._convert_pod_event, master/watcher/k8s_watcher.py:130-193)."""
    meta = pod.get("metadata", {})
    labels = meta.get("labels", {})
    status = pod.get("status", {})
    exit_reason = ""
    for cs in status.get("containerStatuses", []):
        term = (cs.get("state", {}) or {}).get("terminated")
        if term:
            reason = term.get("reason", "")
            code = term.get("exitCode")
            # OOM only on the kernel OOM reason or exit 247; SIGKILL/SIGTERM
            # (137/143 — eviction, platform force-kill) are plain kills and
            # must not trigger the OOM memory bump on relaunch (reference:
            # master/watcher/k8s_watcher.py _get_pod_exit_reason). Drain /
            # hang / kill share WorkerExit.classify with the agent — one
            # exit-code vocabulary, so the diagnosis rules and the relaunch
            # budget see the same truth either way a pod dies.
            if code is not None:
                kind = WorkerExit.classify(
                    code, hang_enabled=_pod_hang_enabled(pod))
            else:
                kind = ""
            if reason == "OOMKilled" or code == 247:
                exit_reason = NodeExitReason.OOM
            elif kind in (NodeExitReason.DRAINED, NodeExitReason.HANG,
                          NodeExitReason.KILLED):
                exit_reason = kind
            elif reason == "Error":
                exit_reason = NodeExitReason.UNKNOWN_ERROR
    return {
        "name": meta.get("name", ""),
        "node_type": labels.get("dlrover-tpu/type", ""),
        "node_id": int(labels.get("dlrover-tpu/node-id", -1)),
        "rank_index": int(labels.get("dlrover-tpu/rank", -1)),
        "status": POD_PHASE_TO_STATUS.get(
            status.get("phase", ""), NodeStatus.UNKNOWN),
        "exit_reason": exit_reason,
        "host_ip": status.get("hostIP", ""),
        "pod_ip": status.get("podIP", ""),
        "terminating": bool(meta.get("deletionTimestamp")),
    }
