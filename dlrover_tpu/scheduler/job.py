"""Job/Node argument model — the parsed form of an ElasticJob spec.

Capability parity: dlrover/python/scheduler/job.py (JobArgs :109 area,
NodeArgs) and the CRD shape in
dlrover/go/operator/api/v1alpha1/elasticjob_types.go:29-123
(distributionStrategy, optimizeMode, enableDynamicSharding, replicaSpecs).
Specs speak TPU: a replica is a TPU host with `chips` attached chips; the
`tpu_topology` field carries the slice shape (e.g. "4x4x8") so schedulers
can request contiguous sub-slices.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from dlrover_tpu.common.constants import (
    DistributionStrategy,
    NodeType,
    OptimizeMode,
    PlatformType,
)
from dlrover_tpu.common.node import NodeGroupResource, NodeResource


@dataclass
class NodeArgs:
    """Per-replica-type launch config (reference: scheduler/job.py NodeArgs)."""

    group_resource: NodeGroupResource = field(
        default_factory=NodeGroupResource)
    auto_scale: bool = True
    restart_count: int = 3
    critical: bool = False
    # Scale bounds for elastic types; 0 max ⇒ fixed at group count.
    min_count: int = 0
    max_count: int = 0


@dataclass
class JobArgs:
    """Everything the master needs to run one job on one platform."""

    platform: str = PlatformType.LOCAL
    namespace: str = "default"
    job_name: str = "job"
    node_args: Dict[str, NodeArgs] = field(default_factory=dict)
    distribution_strategy: str = DistributionStrategy.ALLREDUCE
    optimize_mode: str = OptimizeMode.SINGLE_JOB
    enable_dynamic_sharding: bool = True
    enable_elastic_scheduling: bool = True
    relaunch_always: bool = False      # relaunch even on app error
    remove_exited_node: bool = True
    cluster: str = ""
    user: str = ""
    job_uuid: str = ""
    # TPU slice topology requested for worker hosts, e.g. "2x2x4".
    tpu_topology: str = ""
    image: str = ""
    command: str = ""
    # Arbitrary platform passthrough (tolerations, node selectors, ...).
    extras: Dict[str, Any] = field(default_factory=dict)

    def worker_args(self) -> Optional[NodeArgs]:
        return self.node_args.get(NodeType.WORKER)

    @classmethod
    def from_spec(cls, spec: Dict[str, Any], job_name: str = "",
                  namespace: str = "default",
                  platform: str = PlatformType.LOCAL) -> "JobArgs":
        """Parse an ElasticJob-shaped dict (the CRD `spec` field; reference:
        K8sJobArgs.initilize, scheduler/kubernetes.py:360-441)."""
        args = cls(platform=platform, namespace=namespace,
                   job_name=job_name or spec.get("jobName", "job"))
        args.distribution_strategy = spec.get(
            "distributionStrategy", DistributionStrategy.ALLREDUCE)
        args.optimize_mode = spec.get("optimizeMode", OptimizeMode.SINGLE_JOB)
        args.enable_dynamic_sharding = spec.get("enableDynamicSharding", True)
        args.enable_elastic_scheduling = spec.get(
            "enableElasticScheduling", True)
        args.tpu_topology = spec.get("tpuTopology", "")
        args.image = spec.get("image", "")
        args.command = spec.get("command", "")
        for node_type, replica in spec.get("replicaSpecs", {}).items():
            if node_type not in (NodeType.WORKER, NodeType.PS,
                                 NodeType.CHIEF, NodeType.EVALUATOR):
                continue
            res = replica.get("resource", {})
            group = NodeGroupResource(
                count=int(replica.get("replicas", 0)),
                node_resource=NodeResource(
                    cpu=float(res.get("cpu", 0)),
                    memory_mb=float(res.get("memoryMb", 0)),
                    chips=int(res.get("chips", 0)),
                    chip_type=res.get("chipType", ""),
                    priority=res.get("priority", ""),
                ),
            )
            args.node_args[node_type] = NodeArgs(
                group_resource=group,
                auto_scale=bool(replica.get("autoScale", True)),
                restart_count=int(replica.get("restartCount", 3)),
                critical=bool(replica.get(
                    "critical", node_type == NodeType.PS)),
                min_count=int(replica.get("minReplicas", 0)),
                max_count=int(replica.get("maxReplicas", 0)),
            )
        return args
