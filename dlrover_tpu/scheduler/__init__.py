"""Platform scheduler abstraction (k8s / local / ray-style).

Capability parity: dlrover/python/scheduler/ — `JobArgs` parsed per
platform (scheduler/job.py:109, kubernetes.py:360), platform clients, and
the factory. The local platform is a complete in-memory cluster used by
tests and the standalone path, exactly like the reference's mocked
k8sClient (tests/test_utils.py:238-253) but as a first-class backend.
"""

from dlrover_tpu.scheduler.job import JobArgs, NodeArgs
from dlrover_tpu.scheduler.factory import new_platform_cluster

__all__ = ["JobArgs", "NodeArgs", "new_platform_cluster"]
