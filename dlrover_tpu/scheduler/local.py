"""In-memory "cluster": the local platform backend.

Capability parity: the reference tests' mocked k8sClient
(dlrover/python/tests/test_utils.py:238-253) promoted to a first-class
platform — pod records live in a dict, lifecycle transitions are explicit
method calls, and every change emits a watch event. The standalone
`dlrover-tpu-run` path and all master tests run against this backend, and
a chaos hook (`fail_pod`) gives fault-injection the reference only had via
chaosblade examples.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.constants import NodeEventType, NodeStatus


@dataclass
class PodRecord:
    """One simulated pod/host."""

    name: str
    node_type: str
    node_id: int
    rank_index: int
    status: str = NodeStatus.PENDING
    labels: Dict[str, str] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    resource: Dict[str, Any] = field(default_factory=dict)
    exit_reason: str = ""
    create_time: float = field(default_factory=time.time)


@dataclass
class WatchEvent:
    event_type: str       # NodeEventType
    pod: PodRecord


class LocalCluster:
    """Thread-safe fake cluster with a watch-event stream."""

    def __init__(self, auto_run: bool = True):
        # auto_run: created pods transition PENDING→RUNNING immediately,
        # like a healthy cluster with capacity.
        self._pods: Dict[str, PodRecord] = {}
        self._lock = threading.Lock()
        self._subscribers: List["queue.Queue[WatchEvent]"] = []
        self._auto_run = auto_run
        self._uid = itertools.count()

    # -- pod lifecycle -------------------------------------------------
    def create_pod(self, pod: PodRecord) -> PodRecord:
        with self._lock:
            self._pods[pod.name] = pod
        self._emit(NodeEventType.ADDED, pod)
        if self._auto_run:
            self.set_status(pod.name, NodeStatus.RUNNING)
        return pod

    def delete_pod(self, name: str) -> bool:
        with self._lock:
            pod = self._pods.pop(name, None)
        if pod is None:
            return False
        pod.status = NodeStatus.DELETED
        self._emit(NodeEventType.DELETED, pod)
        return True

    def set_status(self, name: str, status: str,
                   exit_reason: str = "") -> None:
        with self._lock:
            pod = self._pods.get(name)
            if pod is None:
                return
            pod.status = status
            if exit_reason:
                pod.exit_reason = exit_reason
        self._emit(NodeEventType.MODIFIED, pod)

    def fail_pod(self, name: str, exit_reason: str = "") -> None:
        """Chaos hook: make a pod fail (test/fault-injection entry)."""
        self.set_status(name, NodeStatus.FAILED, exit_reason)

    def list_pods(self, node_type: Optional[str] = None) -> List[PodRecord]:
        with self._lock:
            pods = list(self._pods.values())
        if node_type is not None:
            pods = [p for p in pods if p.node_type == node_type]
        return pods

    def get_pod(self, name: str) -> Optional[PodRecord]:
        with self._lock:
            return self._pods.get(name)

    # -- watch stream --------------------------------------------------
    def subscribe(self) -> "queue.Queue[WatchEvent]":
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            self._subscribers.append(q)
        return q

    def unsubscribe(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            if q in self._subscribers:
                self._subscribers.remove(q)

    def _emit(self, event_type: str, pod: PodRecord) -> None:
        with self._lock:
            subscribers = list(self._subscribers)
        for q in subscribers:
            q.put(WatchEvent(event_type, pod))
