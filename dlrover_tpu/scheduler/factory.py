"""Platform factory (reference: dlrover/python/scheduler/factory.py)."""

from __future__ import annotations

from typing import Any

from dlrover_tpu.common.constants import PlatformType


def new_platform_cluster(platform: str, namespace: str = "default",
                         **kwargs: Any) -> Any:
    if platform == PlatformType.LOCAL:
        from dlrover_tpu.scheduler.local import LocalCluster

        return LocalCluster(**kwargs)
    if platform == PlatformType.KUBERNETES:
        from dlrover_tpu.scheduler.kubernetes import K8sClient

        return K8sClient(namespace=namespace, **kwargs)
    raise ValueError(f"unknown platform {platform!r}")
