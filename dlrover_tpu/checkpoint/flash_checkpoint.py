"""Flash checkpoint: async sharded save/restore + data position.

Capability parity: the subsystem the reference names "Flash Checkpoint" but
leaves as a TODO (`ElasticTrainer` checkpoint hook raises NotImplementedError,
dlrover/trainer/torch/elastic/trainer.py:295-319); its FSDP precedents are
`save_fsdp_flat_param`/`ShardOptim`/`ShardTensorUtil` (atorch/utils/
fsdp_save_util.py:98,179,222,364 — safetensors shards + reshard-on-restore)
and the master-side dataset-position checkpoint (`DatasetShardCheckpoint`,
master/shard/base_dataset_manager.py:60).

TPU re-design on Orbax:
- **Async save**: `ocp.CheckpointManager` commits in a background thread;
  the train loop only pays the device→host copy (the same role as the
  reference's shared-memory staging).
- **Reshard-on-restore**: the restore target is an *abstract* state carrying
  the NEW mesh's shardings — Orbax reads each shard from disk directly into
  the new layout, which is the TPU-native equivalent of `ShardTensorUtil`'s
  FSDP→TP conversion. Works across any mesh-shape change (elastic resize).
- **Data position**: a JSON item saved atomically with the model state
  (sampler state_dict + master shard checkpoint), so a restored job resumes
  mid-epoch without replaying or dropping data.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from dlrover_tpu import obs
from dlrover_tpu.common.log import default_logger as logger
from dlrover_tpu.parallel.sharding import mesh_shardings

_MODEL_ITEM = "state"
_DATA_ITEM = "data"
# data-item key marking a quantized state payload (and its bit width)
_QUANT_KEY = "_ckpt_quantized_bits"
# which subtree was encoded: "params" (current saves) or "tree" (legacy
# whole-state layout). Checkpoints missing this key predate it: their
# save quantized params-only iff the state had a .params ATTRIBUTE.
_QUANT_LAYOUT_KEY = "_ckpt_quantized_layout"


def abstract_state_for(init_fn, mesh, rules=None, *args) -> Any:
    """Abstract TrainState (shapes + NEW-mesh shardings) for restore.

    init_fn: the *boxed* state initializer (returns nn.Partitioned-annotated
    pytree); args are example inputs (e.g. a PRNG key).
    """
    abstract = jax.eval_shape(init_fn, *args)
    shardings = mesh_shardings(abstract, mesh, rules)
    import flax.linen as nn

    abstract = nn.unbox(abstract)
    return jax.tree.map(
        lambda leaf, sharding: jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=sharding),
        abstract, shardings,
    )


class FlashCheckpointer:
    """Interval + on-demand async checkpointing of (TrainState, data state).

    One instance per training process; all processes participate in the
    sharded save (each writes its own shards), process 0 writes metadata.
    """

    def __init__(
        self,
        directory: str,
        save_interval_steps: int = 100,
        max_to_keep: int = 3,
        quantize_bits: int = 0,
    ):
        """quantize_bits: 8 or 4 stores eligible float leaves groupwise
        int-quantized (checkpoint/quantized.py) — ~4x fewer restore
        bytes vs fp32 state, the dominant term of at-scale recovery.
        0 = store exact dtypes. Restores auto-detect how a checkpoint
        was written, so flipping the flag mid-job is safe."""
        self._directory = directory
        self._save_interval = save_interval_steps
        self._quantize_bits = quantize_bits
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            enable_async_checkpointing=True,
        )
        self._manager = ocp.CheckpointManager(
            directory, options=options,
            item_names=(_MODEL_ITEM, _DATA_ITEM),
        )
        self._lock = threading.Lock()
        # wall-clock of the last full (dispatch + commit) save, the
        # emergency path's estimate of whether a deadline is winnable;
        # 0 = no evidence yet (guarded by _lock)
        self._last_full_save_s = 0.0
        # per-phase breakdown of the last successful restore (step
        # discovery / metadata read / tensor read / decode, plus bytes
        # and effective bandwidth) — merged into the elastic loop's
        # restore timings and the restore bench's JSON. Written only by
        # the restoring thread; read after restore() returns.
        self.last_restore_phases: Dict[str, float] = {}

    # ------------------------------------------------------------------
    def maybe_save(self, step: int, state: Any,
                   data_state: Optional[Dict[str, Any]] = None,
                   force: bool = False) -> bool:
        """Save if at an interval boundary (or force=True, e.g. membership
        change / preemption notice). Returns whether a save started."""
        if not force and (self._save_interval <= 0
                          or step % self._save_interval != 0 or step == 0):
            return False
        data_state = dict(data_state or {})
        if self._quantize_bits:
            from dlrover_tpu.checkpoint.quantized import encode_tree

            bits = self._quantize_bits
            # encode_tree dispatches small per-leaf jitted programs
            # (cached across saves); PARAMS only — int8 on Adam's second
            # moments wrecks the resumed update (sqrt(nu) denominators
            # amplify the groupwise error; measured: post-resume loss 2x
            # worse), and params carry the bulk of the bytes anyway
            if hasattr(state, "params") and hasattr(state, "replace"):
                state = state.replace(
                    params=encode_tree(state.params, bits))
                data_state[_QUANT_KEY] = bits
                data_state[_QUANT_LAYOUT_KEY] = "params"
            elif isinstance(state, dict) and "params" in state:
                state = {**state, "params": encode_tree(
                    state["params"], bits)}
                data_state[_QUANT_KEY] = bits
                data_state[_QUANT_LAYOUT_KEY] = "params"
            else:
                # no identifiable params subtree: quantizing blindly
                # would hit optimizer moments — save exact instead
                logger.warning(
                    "quantize_bits=%d requested but the state has no "
                    "'params' subtree; saving exact dtypes", bits)
        # span covers the synchronous part only (device→host staging +
        # dispatch); the async commit is awaited in `wait`
        with obs.span("checkpoint_save",
                      {"step": step, "forced": force}) as save_span:
            with self._lock:
                args = ocp.args.Composite(**{
                    _MODEL_ITEM: ocp.args.StandardSave(state),
                    _DATA_ITEM: ocp.args.JsonSave(data_state),
                })
                saved = self._manager.save(step, args=args, force=force)
            save_span.set_attr("saved", saved)
        if saved:
            obs.get_registry().counter(
                "dlrover_tpu_checkpoint_saves_total",
                "Checkpoint saves dispatched").inc()
            logger.info("flash checkpoint: async save started at step %d",
                        step)
        return saved

    def save_emergency(self, step: int, state: Any,
                       data_state: Optional[Dict[str, Any]] = None,
                       deadline: float = 0.0,
                       min_window_s: Optional[float] = None) -> str:
        """Deadline-bounded save on the way out (preemption drain): the
        VM disappears at ``deadline`` (unix ts), so the save must COMMIT
        before then or not start at all. Returns the outcome:

        - ``"saved"``   — dispatched and committed inside the window;
        - ``"skipped"`` — window too small (below ``min_window_s``, or
          below the last observed full-save wall time): a save that
          cannot commit only produces a torn step the restore fallback
          then has to walk past — skip loudly instead;
        - ``"timeout"`` — dispatched but the commit did not finish in
          time; the step MAY be torn (the restore fallback handles it),
          logged as such;
        - ``"noop"``    — nothing dispatched (Orbax declined the save).

        Counted in ``dlrover_tpu_checkpoint_emergency_total{outcome}``.
        """
        import time as _time

        if min_window_s is None:
            from dlrover_tpu.common.config import Context

            min_window_s = Context.singleton().emergency_ckpt_min_window_s
        now = _time.time()
        remaining = deadline - now if deadline > 0 else float("inf")
        with self._lock:
            estimate = self._last_full_save_s
        if remaining < max(min_window_s, estimate):
            logger.error(
                "emergency checkpoint at step %d SKIPPED: %.1fs left "
                "before the deadline (< floor %.1fs / last full save "
                "%.1fs) — resume will fall back to the last committed "
                "step", step, remaining, min_window_s, estimate)
            outcome = "skipped"
        else:
            t0 = _time.monotonic()
            with obs.span("emergency_checkpoint",
                          {"step": step,
                           "window_s": round(min(remaining, 1e9), 1)}
                          ) as em_span:
                # an interval save may already be in flight for this
                # very step (drain landing on a boundary); re-saving
                # the step would make Orbax refuse — just await it
                if self.latest_step() == step:
                    saved = True
                    dispatched = False
                else:
                    saved = self.maybe_save(step, state, data_state,
                                            force=True)
                    dispatched = saved
                if not saved:
                    outcome = "noop"
                else:
                    # bounded commit wait: Orbax has no timeout, so park
                    # the join on a side thread and give it what's left
                    # of the window (minus a margin to exit cleanly)
                    waiter = threading.Thread(
                        target=self._wait_quietly, daemon=True)
                    waiter.start()
                    budget = (max(0.5, deadline - _time.time() - 0.5)
                              if deadline > 0 else None)
                    waiter.join(budget)
                    if waiter.is_alive():
                        outcome = "timeout"
                        logger.error(
                            "emergency checkpoint at step %d: commit "
                            "still running at the deadline — the step "
                            "may be torn (restore falls back past it)",
                            step)
                    else:
                        outcome = "saved"
                        # only a save THIS call dispatched measures a
                        # full save — the await-in-flight branch would
                        # record just the residual commit tail and
                        # poison the skip-floor estimate
                        if dispatched:
                            with self._lock:
                                self._last_full_save_s = (
                                    _time.monotonic() - t0)
                em_span.set_attr("outcome", outcome)
        obs.get_registry().counter(
            "dlrover_tpu_checkpoint_emergency_total",
            "Deadline-bounded emergency saves by outcome",
            labelnames=("outcome",)).labels(outcome=outcome).inc()
        obs.get_flight_recorder().record_event(
            "emergency_checkpoint", step=step, outcome=outcome,
            window_s=round(min(remaining, 1e9), 1))
        if outcome == "saved":
            logger.info("emergency checkpoint committed at step %d "
                        "(%.1fs window)", step, remaining)
        return outcome

    def _wait_quietly(self) -> None:
        try:
            self._manager.wait_until_finished()
        except Exception:  # noqa: BLE001 — the drain path must not die
            logger.exception("emergency checkpoint commit failed")

    def restore(self, abstract_state: Any
                ) -> Optional[Tuple[Any, Dict[str, Any], int]]:
        """Restore the newest restorable checkpoint INTO the abstract
        state's shardings (reshard-on-restore). Returns
        (state, data_state, step) or None when no checkpoint exists.

        Fallback chain: a corrupt/partial newest step (an Orbax raise —
        torn save, preempted commit, bit rot) is logged loudly, counted
        in ``dlrover_tpu_checkpoint_restore_fallbacks_total``, and the
        next-older step is tried — the trainer resumes slightly further
        back instead of crash-looping on poison. Only when EVERY step
        fails does the last error propagate (silently reinitializing
        from scratch would throw away the job's progress).

        Quantized checkpoints are detected from the data item's marker
        (written by maybe_save), decoded on device into the abstract
        state's dtypes + shardings. The per-phase wall-clock (step
        discovery, metadata read, tensor read, decode) lands in
        ``last_restore_phases`` with bytes restored and effective
        bandwidth — the measured baseline the peer-to-peer restore work
        (ROADMAP item 1) starts from."""
        import time as _time

        self._begin_restore()
        t0 = _time.monotonic()
        with obs.span("restore_step_discovery"):
            steps = sorted(self._manager.all_steps() or (), reverse=True)
        discovery_s = _time.monotonic() - t0
        if not steps:
            return None
        first_exc: Optional[Exception] = None
        failed_steps = []
        for nth, step in enumerate(steps):
            try:
                with obs.span("checkpoint_restore",
                              {"step": step, "fallback": nth > 0}):
                    result = self._restore_at(step, abstract_state)
            except Exception as e:  # noqa: BLE001 — Orbax raise varies
                # keep the NEWEST step's error for the final raise: when
                # every step fails the same systematic way (e.g. a
                # restore-target shape mismatch), that's the one the
                # operator needs, not the oldest retained step's
                first_exc = first_exc if first_exc is not None else e
                failed_steps.append(step)
                logger.error(
                    "checkpoint restore at step %d FAILED (%s: %s); "
                    "falling back to the next-older step", step,
                    type(e).__name__, e)
                obs.get_registry().counter(
                    "dlrover_tpu_checkpoint_restore_fallbacks_total",
                    "Corrupt/partial checkpoints skipped during "
                    "restore").inc()
                continue
            if failed_steps:
                self._remove_failed_steps(failed_steps)
            obs.get_registry().counter(
                "dlrover_tpu_checkpoint_restores_total",
                "Checkpoint restores completed").inc()
            self.last_restore_phases["step_discovery_s"] = round(
                discovery_s, 3)
            self._publish_restore_stats(step)
            return result
        raise first_exc

    def _publish_restore_stats(self, step: int) -> None:
        """Bytes restored + effective read bandwidth of the step that
        just restored, as gauges and into ``last_restore_phases``. The
        bandwidth denominator is the tensor-read phase alone — the
        number peer-to-peer restore has to beat."""
        import os

        phases = self.last_restore_phases
        total_bytes = 0
        step_dir = os.path.join(str(self._directory), str(step))
        try:
            for root, _, files in os.walk(step_dir):
                total_bytes += sum(
                    os.path.getsize(os.path.join(root, name))
                    for name in files)
        except OSError:
            return
        phases["restored_bytes"] = float(total_bytes)
        read_s = phases.get("tensor_read_s", 0.0)
        if read_s > 0 and total_bytes > 0:
            phases["read_bandwidth_mbps"] = round(
                total_bytes / (1 << 20) / read_s, 2)
        # source-labeled: the peer-restore path publishes the same
        # gauges as source="peer" — an unlabeled series would let one
        # path silently overwrite the other's last reading
        registry = obs.get_registry()
        registry.gauge(
            "dlrover_tpu_checkpoint_restore_bytes",
            "Bytes read by the last checkpoint restore",
            labelnames=("source",),
        ).labels(source="orbax").set(float(total_bytes))
        if phases.get("read_bandwidth_mbps"):
            registry.gauge(
                "dlrover_tpu_checkpoint_restore_bandwidth_mbps",
                "Effective bandwidth of the last restore's "
                "tensor-read phase",
                labelnames=("source",),
            ).labels(source="orbax").set(phases["read_bandwidth_mbps"])

    def _remove_failed_steps(self, steps) -> None:
        """Drop the corrupt newer steps a fallback skipped: the resumed
        trainer re-reaches those step numbers and Orbax refuses to save
        into an existing step directory — leaving the poison in place
        would re-crash the very job the fallback just rescued."""
        import os
        import shutil

        for step in steps:
            try:
                self._manager.delete(step)
            except Exception:  # noqa: BLE001 — metadata may be torn too
                shutil.rmtree(os.path.join(str(self._directory),
                                           str(step)),
                              ignore_errors=True)
            logger.warning(
                "checkpoint: removed unrestorable step %d (resumed "
                "training will rewrite it)", step)

    def restore_data_state(self, step: int) -> Optional[Dict[str, Any]]:
        """Just the tiny JSON data item of one committed step (sampler
        position + master shard checkpoint), markers stripped — the
        peer-restore path's fallback when no donor manifest carries the
        data position. None when the step/item is unreadable."""
        try:
            data = self._manager.restore(
                step, args=ocp.args.Composite(**{
                    _DATA_ITEM: ocp.args.JsonRestore()}),
            )[_DATA_ITEM] or {}
        except Exception:  # noqa: BLE001 — Orbax raise varies
            return None
        data = dict(data)
        data.pop(_QUANT_KEY, None)
        data.pop(_QUANT_LAYOUT_KEY, None)
        return data

    def _begin_restore(self) -> None:
        """Sole writer of ``last_restore_phases`` (single-threaded by
        contract: only the restoring thread, and read after return)."""
        self.last_restore_phases = {}

    def restore_step(self, step: int, abstract_state: Any
                     ) -> Tuple[Any, Dict[str, Any], int]:
        """Restore ONE specific committed step — no newest-first
        fallback walk. The peer-restore mixed path uses it to read only
        the shards no surviving replica holds, at exactly the step the
        peers staged (mixing steps would assemble a state that never
        existed)."""
        self._begin_restore()
        return self._restore_at(step, abstract_state)

    def _restore_at(self, step: int, abstract_state: Any
                    ) -> Tuple[Any, Dict[str, Any], int]:
        import time as _time

        phases = self.last_restore_phases
        # the tiny JSON item first: it says how the state was encoded
        t0 = _time.monotonic()
        with obs.span("restore_metadata_read", {"step": step}):
            data = self._manager.restore(
                step, args=ocp.args.Composite(**{
                    _DATA_ITEM: ocp.args.JsonRestore()}),
            )[_DATA_ITEM] or {}
        phases["metadata_read_s"] = round(_time.monotonic() - t0, 3)
        bits = int(data.pop(_QUANT_KEY, 0))
        if bits:
            from dlrover_tpu.checkpoint.quantized import (
                abstract_encoded,
                decode_tree,
            )

            # the SAVED layout decides the decode shape — not the restore
            # target's. Checkpoints written before the layout key existed
            # carry only the quant marker: their save quantized
            # params-only iff the state had a .params attribute, so infer
            # that rule from the restore target — loudly, because on a
            # corrupted data item the inference can be wrong (a wrong
            # guess fails the decode's leaf-count/shape checks rather
            # than restoring silently corrupt state).
            layout = data.pop(_QUANT_LAYOUT_KEY, "")
            if not layout:
                layout = ("params" if hasattr(abstract_state, "params")
                          else "tree")
                logger.warning(
                    "checkpoint step %s: quantized marker without %s "
                    "(legacy save); inferring layout=%r from the restore "
                    "target", step, _QUANT_LAYOUT_KEY, layout)

            def _restore_encoded(target):
                t_read = _time.monotonic()
                with obs.span("restore_tensor_read",
                              {"step": step, "quantized_bits": bits}):
                    encoded = self._manager.restore(
                        step, args=ocp.args.Composite(**{
                            _MODEL_ITEM: ocp.args.StandardRestore(
                                target)}),
                    )[_MODEL_ITEM]
                phases["tensor_read_s"] = round(
                    _time.monotonic() - t_read, 3)
                return encoded

            if layout == "params" and hasattr(abstract_state, "params") \
                    and hasattr(abstract_state, "replace"):
                encoded = _restore_encoded(abstract_state.replace(
                    params=abstract_encoded(abstract_state.params,
                                            bits)))
                t_decode = _time.monotonic()
                with obs.span("restore_decode", {"bits": bits}):
                    state = encoded.replace(params=decode_tree(
                        encoded.params, abstract_state.params, bits))
            elif (layout == "params"
                  and isinstance(abstract_state, dict)
                  and "params" in abstract_state):
                encoded = _restore_encoded(
                    {**abstract_state, "params": abstract_encoded(
                        abstract_state["params"], bits)})
                t_decode = _time.monotonic()
                with obs.span("restore_decode", {"bits": bits}):
                    state = {**encoded, "params": decode_tree(
                        encoded["params"], abstract_state["params"],
                        bits)}
            else:
                # whole-tree layout: decode every encoded node in place
                encoded = _restore_encoded(
                    abstract_encoded(abstract_state, bits))
                t_decode = _time.monotonic()
                with obs.span("restore_decode", {"bits": bits}):
                    state = decode_tree(encoded, abstract_state, bits)
            # dispatch cost only — the decoded arrays materialize under
            # the caller's device-put/block phase
            phases["decode_s"] = round(_time.monotonic() - t_decode, 3)
        else:
            t_read = _time.monotonic()
            with obs.span("restore_tensor_read", {"step": step}):
                state = self._manager.restore(
                    step, args=ocp.args.Composite(**{
                        _MODEL_ITEM: ocp.args.StandardRestore(
                            abstract_state)}),
                )[_MODEL_ITEM]
            phases["tensor_read_s"] = round(
                _time.monotonic() - t_read, 3)
        logger.info("flash checkpoint: restored step %d%s", step,
                    f" (int{bits} quantized)" if bits else "")
        return state, data, step

    # ------------------------------------------------------------------
    def wait(self) -> None:
        """Block until in-flight async saves are committed."""
        self._manager.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._manager.latest_step()

    def all_steps(self):
        return self._manager.all_steps()

    def close(self) -> None:
        self._manager.wait_until_finished()
        self._manager.close()

    def __enter__(self) -> "FlashCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
