"""Quantized checkpoint codec: int8/int4 state trees for flash checkpoints.

Capability parity: the reference ships a CUDA quantization library whose
flagship consumer is communication/storage compression
(atorch/atorch/ops/csrc/quantization/quant_reduce.cu:248); here the same
groupwise-symmetric scheme (ops/quantization.py) compresses the
checkpoint itself — int8 cuts restore bytes ~4x vs fp32 (~2x vs bf16),
which is exactly the term that dominates kill→first-step recovery time
at multi-GB scale.

Design: a pure codec over pytrees, composed by FlashCheckpointer.

- ``encode_tree(state)``: every *eligible* float leaf (ndim >= 1, last
  dim divisible by the group size) becomes ``{"__quant__", "q", "s"}``
  — int8 codes + fp32 groupwise scales; everything else (int counters,
  scalars, ragged tails) rides along raw. The transform is jittable and
  runs on device, so a sharded train state quantizes shard-locally with
  no gather.
- ``abstract_encoded(abstract_state)``: the matching abstract target for
  Orbax's reshard-on-restore — ``q`` keeps the leaf's partitioning on
  every dim but the (group-quantized) last one, so multi-GB restores
  still stream shard-parallel from disk; scales are tiny and land
  replicated.
- ``decode_tree(encoded, abstract_state)``: dequantize + cast back,
  jitted with the target shardings (the reshard happens inside XLA).

Eligibility is a pure function of the abstract state, so the save and
restore sides always agree on the tree structure.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from dlrover_tpu.ops.quantization import pack_int4, unpack_int4

_TAG = "__quant__"
DEFAULT_GROUP = 128


def _mode(leaf: Any, group_size: int) -> str:
    """row: groupwise over the (divisible) last dim, layout preserved —
    big matmul weights keep their partitioning, so multi-GB restores
    stream shard-parallel. flat: flatten + zero-pad to the group size —
    catches ragged/small-last-dim leaves (embeddings, odd heads) at the
    cost of a replicated restore. raw: not worth compressing."""
    dtype = jnp.dtype(leaf.dtype)
    if not (jnp.issubdtype(dtype, jnp.floating)
            and getattr(leaf, "ndim", 0) >= 1):
        return "raw"
    if leaf.shape[-1] % group_size == 0 and leaf.shape[-1] > 0:
        return "row"
    size = int(np.prod(leaf.shape))
    if size >= group_size:
        return "flat"
    return "raw"


def _is_encoded(node: Any) -> bool:
    return isinstance(node, dict) and _TAG in node


def _quantize_groups(x2: jax.Array, qmax: int) -> tuple:
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = absmax / qmax
    inv = jnp.where(scale > 0, 1.0 / scale, 0.0)
    q = jnp.clip(jnp.round(x2 * inv), -qmax, qmax).astype(jnp.int8)
    return q, scale


def _quantize_leaf(x: jax.Array, bits: int, group_size: int,
                   mode: str) -> dict:
    qmax = 127 if bits == 8 else 7
    if mode == "flat":
        flat = x.reshape(-1).astype(jnp.float32)
        pad = (-flat.shape[0]) % group_size
        if pad:
            flat = jnp.pad(flat, (0, pad))
        x2 = flat.reshape(-1, group_size)
    else:
        x2 = x.reshape(-1, group_size).astype(jnp.float32)
    q, scale = _quantize_groups(x2, qmax)
    if mode == "flat":
        q = q.reshape(-1)
        scales = scale.reshape(-1)
    else:
        q = q.reshape(x.shape)
        scales = scale.reshape(x.shape[:-1] + (x.shape[-1] // group_size,))
    if bits == 4:
        q = pack_int4(q)
    return {_TAG: jnp.asarray(bits, jnp.int32), "q": q, "s": scales}


def _dequantize_leaf(node: dict, target: Any, bits: int,
                     group_size: int, mode: str) -> jax.Array:
    q = node["q"]
    if bits == 4:
        q = unpack_int4(q)
    if mode == "flat":
        q2 = q.reshape(-1, group_size)
        s2 = node["s"].reshape(-1, 1)
        out = (q2.astype(jnp.float32) * s2).reshape(-1)
        size = int(np.prod(target.shape))
        return out[:size].astype(target.dtype).reshape(target.shape)
    groups = node["s"].shape[-1]
    q2 = q.reshape(-1, q.shape[-1] // groups)
    s2 = node["s"].reshape(-1, 1)
    out = (q2.astype(jnp.float32) * s2).astype(target.dtype)
    return out.reshape(target.shape)


@functools.lru_cache(maxsize=None)
def _jitted_quantizer(bits: int, group_size: int, mode: str):
    return jax.jit(functools.partial(
        _quantize_leaf, bits=bits, group_size=group_size, mode=mode))


@functools.lru_cache(maxsize=None)
def _jitted_dequantizer(bits: int, group_size: int, mode: str,
                        shape, dtype):
    target = jax.ShapeDtypeStruct(shape, dtype)
    return jax.jit(lambda q, s: _dequantize_leaf(
        {_TAG: bits, "q": q, "s": s}, target, bits, group_size, mode))


def encode_tree(state: Any, bits: int = 8,
                group_size: int = DEFAULT_GROUP) -> Any:
    """Quantize eligible leaves on device, one small jitted program per
    unique (shape, mode) — NOT one whole-tree program: a mega-program
    with hundreds of big-tensor outputs is exactly the compile that
    stalls remote-compile backends (observed wedging the axon tunnel),
    and the per-leaf programs hit jit's cache across leaves and saves."""
    if bits not in (8, 4):
        raise ValueError(f"checkpoint quantization bits must be 8 or 4, "
                         f"got {bits}")

    def _leaf(leaf):
        mode = _mode(leaf, group_size)
        if mode == "raw":
            return leaf
        return _jitted_quantizer(bits, group_size, mode)(leaf)

    return jax.tree.map(_leaf, state)


def abstract_encoded(abstract_state: Any, bits: int = 8,
                     group_size: int = DEFAULT_GROUP) -> Any:
    """Abstract (ShapeDtypeStruct) target matching encode_tree's output,
    carrying restore shardings derived from the abstract state's."""

    def _leaf(leaf):
        mode = _mode(leaf, group_size)
        if mode == "raw":
            return leaf
        sharding = getattr(leaf, "sharding", None)
        q_sharding = s_sharding = r_sharding = None
        if isinstance(sharding, NamedSharding):
            s_sharding = NamedSharding(sharding.mesh, P())
            r_sharding = s_sharding
            if mode == "row":
                # keep every partitioned dim but the last (its groups may
                # not divide by the axis); scales/tag are tiny → replicated
                spec = list(sharding.spec) + [None] * (
                    leaf.ndim - len(sharding.spec))
                spec[-1] = None
                q_sharding = NamedSharding(sharding.mesh, P(*spec))
            else:
                q_sharding = s_sharding
        if mode == "flat":
            size = int(np.prod(leaf.shape))
            padded = size + (-size) % group_size
            q_shape = (padded // (2 if bits == 4 else 1),)
            s_shape = (padded // group_size,)
        else:
            q_shape = leaf.shape[:-1] + (
                leaf.shape[-1] // (2 if bits == 4 else 1),)
            s_shape = leaf.shape[:-1] + (leaf.shape[-1] // group_size,)
        return {
            _TAG: jax.ShapeDtypeStruct((), jnp.int32,
                                       sharding=r_sharding),
            "q": jax.ShapeDtypeStruct(q_shape, jnp.int8,
                                      sharding=q_sharding),
            "s": jax.ShapeDtypeStruct(s_shape, jnp.float32,
                                      sharding=s_sharding),
        }

    return jax.tree.map(_leaf, abstract_state)


def decode_tree(encoded: Any, abstract_state: Any, bits: int = 8,
                group_size: int = DEFAULT_GROUP) -> Any:
    """Dequantize back into the abstract state's dtypes + shardings —
    per-leaf jitted programs (see encode_tree), with each result
    device_put into the target's sharding when one is given."""
    enc_leaves = jax.tree.leaves(encoded, is_leaf=_is_encoded)
    targets, treedef = jax.tree.flatten(abstract_state)
    assert len(enc_leaves) == len(targets), (
        f"encoded tree has {len(enc_leaves)} leaves, target "
        f"{len(targets)} — quantization eligibility drifted between "
        f"save and restore")

    out = []
    for node, target in zip(enc_leaves, targets):
        if _is_encoded(node):
            mode = _mode(target, group_size)
            fn = _jitted_dequantizer(bits, group_size, mode,
                                     tuple(target.shape),
                                     jnp.dtype(target.dtype))
            leaf = fn(node["q"], node["s"])
        else:
            leaf = jnp.asarray(node, target.dtype)
        sharding = getattr(target, "sharding", None)
        if isinstance(sharding, NamedSharding):
            leaf = jax.device_put(leaf, sharding)
        out.append(leaf)
    return jax.tree.unflatten(treedef, out)


def encoded_nbytes(encoded: Any) -> int:
    """Serialized payload bytes of an (abstract or concrete) tree."""
    return sum(
        int(np.prod(leaf.shape)) * jnp.dtype(leaf.dtype).itemsize
        for leaf in jax.tree.leaves(encoded)
        if hasattr(leaf, "shape"))
